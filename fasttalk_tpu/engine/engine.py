"""The TPU inference engine: continuous batching over fixed decode slots.

This replaces the external vLLM/Ollama containers of the reference with an
in-process JAX engine (SURVEY.md §7 design stance: the engine is an
in-process library behind the same async-generator seam the reference
handlers exposed, vllm_handler.py:216-225).

Architecture (JetStream-style, XLA-first):

- **Fixed shapes.** S decode slots; one jitted decode step advances all
  slots at once. Prefill is chunked into power-of-two buckets; each bucket
  compiles once. KV-length buckets bound attention cost: the decode step
  is compiled per cache-prefix length in {512, 1024, ...} and the engine
  picks the smallest bucket covering the longest active sequence.
- **Donated KV cache.** The cache pytree is donated through every jitted
  call, so K/V updates happen in place in HBM. Idle slots are excluded
  from cache writes by a per-slot write mask, so a parked session's
  resident KV can never be clobbered by the batched step.
- **Single engine thread** owns every device interaction; asyncio callers
  talk to it through a command queue, and token deltas travel back via
  ``loop.call_soon_threadsafe`` onto per-request ``asyncio.Queue``s. A
  generation is therefore fully async on the serving side — the
  event-loop-stalling sync-generator bug of the reference
  (websocket_server_vllm.py:578, SURVEY.md §3.3 warning) cannot occur.
- **Device-resident decode state, multi-token calls, pipelined dispatch.**
  Positions, active mask, per-slot sampling params, the current token and
  the PRNG key all live on the device and are chained call-to-call; one
  jitted call runs ``steps_per_call`` decode steps under ``lax.scan`` and
  returns all sampled tokens, and up to ``pipeline_depth`` calls stay in
  flight so the host-side fetch/detokenise of call N overlaps the device
  compute of call N+1. Host mirrors are reconciled (and re-uploaded) only
  when the slot set changes — request admission, completion, cancel. A
  slot that finishes mid-call keeps decoding garbage until the pipeline
  drains; those tokens are dropped on the host and their (masked or
  past-the-kept-length) KV writes are never attended to.
- **Mid-decode cancellation.** Cancel is a command; the engine deactivates
  the slot at the next step boundary, freeing capacity immediately
  (reference flaw: cancel could not even be received until generation
  completed, SURVEY.md §3.6).
- **KV residency across turns.** Sessions pin slots (engine/slots.py);
  a follow-up turn prefills only the token delta after prefix matching.
- **Shared-prefix KV.** A fresh session whose prompt starts with rows
  resident in ANOTHER slot (common system prompt) gets them by device
  copy — cross-session at admission, and intra-batch for cold bursts
  (leader prefills, members stamp; see _prefill_batched_shared).
- **Speculative decoding** (default "auto"): on-device prompt-lookup
  drafts verified as multi-token scatter-decode blocks, exactly
  distribution-preserving; the dispatcher engages them per call from
  the measured acceptance EMA (see _get_spec_decode_fn,
  _spec_call_wanted and docs/SPEC_DECODE.md).
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncGenerator

import jax
import jax.numpy as jnp
import numpy as np

from fasttalk_tpu.engine.slots import Slot, SlotManager, _lcp
from fasttalk_tpu.engine.tokenizer import StreamDetokenizer, Tokenizer
from fasttalk_tpu.kvcache import (HostKVPool, KVOffloader, RestorePolicy,
                                  entry_problem, kv_env_defaults,
                                  strip_device)
from fasttalk_tpu.kvcache.blocks import BlockAllocator, blocks_for
from fasttalk_tpu.kvcache.radix import RadixTree
from fasttalk_tpu.kvcache.offload import (kv_bucket, make_kv_restore_fn,
                                          make_kv_slice_fn,
                                          make_paged_kv_restore_fn,
                                          make_paged_kv_slice_fn,
                                          pad_rows)
from fasttalk_tpu.models.configs import ModelConfig
from fasttalk_tpu.models.llama import (KVCache, forward, forward_decode,
                                       init_cache, init_paged_cache)
from fasttalk_tpu.observability.events import get_events
from fasttalk_tpu.observability.perf import get_perf, program_key
from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.observability.slo import get_slo
from fasttalk_tpu.observability.trace import get_tracer
from fasttalk_tpu.ops.sampling import (apply_penalties, penalize_values,
                                       sample_tokens)
from fasttalk_tpu.scheduling.scheduler import RequestScheduler
from fasttalk_tpu.structured.compiler import (FSMCompiler,
                                              StructuredError,
                                              validate_structured_spec)
from fasttalk_tpu.structured.fsm import FSMTooLarge, TokenFSM
from fasttalk_tpu.structured.runtime import (ArenaFull, FSMArena,
                                             pack_mask_row)
from fasttalk_tpu.utils.errors import (ENGINE_SHED_CODES,
                                       AdmissionRejected, ErrorCategory,
                                       LLMServiceError)
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("engine")

_KV_BUCKETS = (512, 1024, 2048, 4096, 8192, 16384, 32768)
_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048)


@dataclass
class GenerationParams:
    temperature: float = 0.7
    top_k: int = 40
    top_p: float = 0.9
    max_tokens: int = 2048
    stop: list[str] = field(default_factory=list)
    # Penalties against the current generation's emitted tokens, applied
    # on device by ops/sampling.apply_penalties. Neutral at the engine
    # seam (1.0 / 0.0 / 0.0); the serving layer defaults repeat_penalty
    # to 1.1 (Config), matching the Ollama engine-side default the
    # reference silently relied on.
    repeat_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # vLLM-parity extension (SamplingParams.ignore_eos): decode to the
    # token budget instead of stopping on EOS — fixed-length benching
    # and forced continuation.
    ignore_eos: bool = False
    # Disaggregated prefill tier (router/disagg.py): run ONLY the
    # prompt's chunked prefill, park the finished KV rows to the host
    # pool, and finish with reason "prefill_parked" — no first-token
    # sample, no decode-slot occupancy. The router then migrates the
    # parked entry to a decode replica over /kv/parked. Internal to
    # the router handoff; not client-settable through serving.
    prefill_only: bool = False

    def __post_init__(self) -> None:
        # Client-reachable values: apply_penalties DIVIDES by
        # repeat_penalty, so 0/negative/NaN would poison the whole
        # generation with inf logits rather than erroring. Raising here
        # surfaces as a 400 on /v1 and an invalid_config error frame on
        # the WS (caught before the circuit breaker — a client-shape
        # error must not open the shared breaker, serving/server.py).
        import math

        if not (math.isfinite(self.repeat_penalty)
                and 0.0 < self.repeat_penalty <= 2.0):
            raise ValueError(
                f"repeat_penalty must be in (0, 2], got "
                f"{self.repeat_penalty}")
        if not math.isfinite(self.presence_penalty):
            raise ValueError("presence_penalty must be finite")
        if not math.isfinite(self.frequency_penalty):
            raise ValueError("frequency_penalty must be finite")
        if self.priority not in ("interactive", "bulk"):
            raise ValueError(
                f"priority must be 'interactive' or 'bulk', "
                f"got {self.priority!r}")
        if self.deadline_s is not None:
            try:
                ok = math.isfinite(self.deadline_s) and self.deadline_s > 0
            except TypeError:
                ok = False
            if not ok:
                raise ValueError(
                    f"deadline_s must be a positive number, "
                    f"got {self.deadline_s!r}")
        if self.prefill_only and self.structured is not None:
            raise ValueError(
                "prefill_only is incompatible with structured output "
                "(the FSM samples the first token under its start-state "
                "mask; a prefill-tier request never samples)")
        if self.structured is not None:
            # Shape errors surface here (400 / invalid_config);
            # compile errors surface at the engine seam the same way.
            self.structured = validate_structured_spec(self.structured)
            if self.ignore_eos:
                raise ValueError(
                    "structured output is incompatible with "
                    "ignore_eos=true (the FSM decides where the "
                    "document ends)")
            if self.stop:
                raise ValueError(
                    "structured output is incompatible with stop "
                    "sequences: a stop string could truncate the "
                    "document mid-grammar and break the validity "
                    "guarantee")
    # Text-completion mode (/v1/completions): the prompt is the joined
    # message content, tokenized verbatim (BOS + bytes, no chat
    # template). Out of band on purpose — an in-band role sentinel
    # would let chat clients bypass the template.
    raw_prompt: bool = False
    # Admission-control class and queue TTL (scheduling/scheduler.py):
    # "interactive" admits before "bulk"; deadline_s bounds how long
    # the request may wait in the admission queue before it is expired
    # with a terminal event (None = the scheduler's configured
    # default). Client-settable per session/request.
    priority: str = "interactive"
    deadline_s: float | None = None
    # Constrained decoding (docs/STRUCTURED.md): a structured spec
    # ({"kind": "json_object" | "json_schema" | "regex" | "tool_call",
    # ...}) compiled to a token FSM whose allowed-token mask is applied
    # inside the jitted sampler every step. None = unconstrained (the
    # zero-cost default). Validated here so a malformed spec surfaces
    # as a 400 / invalid_config, never a 500.
    structured: Any = None
    # Per-token journey waterfall (observability/journey.py): when set,
    # the engine stamps each token event with its device-fetch /
    # detok-emit boundaries (the "j" dict) so the serving layer can cut
    # TTFT and inter-token gaps into named hops. Off by default — two
    # time.monotonic() calls per retirement are cheap but not free.
    journey: bool = False


def raw_prompt_text(messages: list[dict]) -> str:
    """The raw completion prompt for ``raw_prompt=True``: joined message
    content. One definition for every backend (tpu/vllm/ollama must
    produce the same prompt for the same request)."""
    return "".join(str(m.get("content") or "") for m in messages)


@dataclass
class _PrefillState:
    """A long prompt being prefilled chunk-by-chunk, interleaved with
    decode calls so running sessions keep streaming (one chunk per engine
    loop iteration; the reference's analogue was head-of-line blocking
    the whole gateway on a single HTTP request)."""

    req: "_Request"
    slot: Slot
    start: int
    todo: list[int]
    t0: float = field(default_factory=time.monotonic)
    last_logits: Any = None


@dataclass
class _Request:
    request_id: str
    session_id: str
    prompt_tokens: list[int]
    params: GenerationParams
    out_queue: asyncio.Queue
    loop: asyncio.AbstractEventLoop
    submitted_at: float = field(default_factory=time.monotonic)
    detok: StreamDetokenizer | None = None
    slot: Slot | None = None
    generated: int = 0
    pending_text: str = ""     # held back for stop-string matching
    emit_buf: str = ""         # text batched within one retirement
    first_token_at: float | None = None
    first_pending: bool = False  # first sampled token not yet fetched
    cancelled: bool = False
    finished: bool = False
    # Observability timestamps/accumulators (observability/trace.py):
    # written only at phase transitions or with O(ns) per-token adds.
    admitted_at: float | None = None    # popped from the waiting queue
    decode_started_at: float | None = None  # activation (prefill done)
    last_token_at: float | None = None  # inter-token gap tracking
    detok_s: float = 0.0                # cumulative detokenize time
    spec_accepted: int = 0              # accepted draft tokens
    spec_drafted: int = 0               # drafts offered to verification
    # Watchdog/SLO stamps (observability/watchdog.py, slo.py):
    last_progress_at: float | None = None  # any forward progress
    max_gap_ms: float = 0.0             # worst inter-token gap seen
    stall_failed: bool = False          # terminated by the watchdog
    slo_recorded: bool = False          # sample already fed to the SLO
    prefill_tokens: int = 0             # tokens actually prefilled
    #   (after resident/restored/shared reuse) — feeds the restore
    #   policy's measured prefill-throughput EMA (kvcache/policy.py)
    # Constrained decoding (docs/STRUCTURED.md): the compiled token
    # FSM, its arena registration, and the HOST-side mirror of the
    # per-slot FSM state (replayed token-by-token at retirement; the
    # authoritative copy advances on device inside the decode scan).
    fsm: TokenFSM | None = None
    fsm_entry: Any = None               # structured/runtime._Entry
    fsm_state: int = 0                  # local (per-FSM) state id
    jump_tokens: int = 0                # tokens emitted by jump-forward


class EngineBase:
    """The engine seam the serving layer depends on. Mirrors the surface
    of the reference's backend handlers (generate stream + connection
    check + model info + cancel, vllm_handler.py:117-326) as one async
    interface; tests substitute a FakeEngine."""

    # Disaggregated-serving replica role (router/disagg.py): "mixed"
    # serves prefill + decode (today's behaviour); "prefill" admits
    # ONLY prefill_only handoff requests (zero decode-slot occupancy);
    # "decode" is a placement hint — the engine itself admits
    # everything. Set by the fleet builder, read by the role gate in
    # TPUEngine.generate.
    role: str = "mixed"

    async def generate(self, request_id: str, session_id: str,
                       messages: list[dict], params: GenerationParams,
                       ) -> AsyncGenerator[dict, None]:
        raise NotImplementedError
        yield  # pragma: no cover

    def cancel(self, request_id: str) -> bool:
        raise NotImplementedError

    def release_session(self, session_id: str) -> None:
        raise NotImplementedError

    def check_connection(self) -> bool:
        raise NotImplementedError

    def get_model_info(self) -> dict:
        raise NotImplementedError

    def get_stats(self) -> dict:
        raise NotImplementedError

    def start(self) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        raise NotImplementedError

    def warmup(self, level: str = "off") -> None:
        """Pre-compile hot shapes before serving traffic (no-op by
        default; the TPU engine overrides)."""

    def begin_drain(self) -> None:
        """Graceful-drain mode: reject NEW submissions (with a
        retry_after hint) while in-flight and already-queued requests
        finish. No-op by default; engines with admission control
        override. Wired into server shutdown (serving/server.py)."""

    def pending_requests(self) -> int:
        """Requests still queued or running (drain-progress probe)."""
        return 0

    def set_trace_component(self, component: str) -> None:
        """Tag this engine's spans with a fleet component name (e.g.
        ``inproc-0``) so in-proc replicas sharing one process tracer
        stay distinguishable in stitched traces (observability/
        stitch.py). No-op by default; engines that hold a tracer
        override by rebinding it to ``get_tracer().scoped(name)``."""

    # ---- fleet fabric: cross-replica KV migration (docs/ROUTER.md).
    # Engines without a host pool answer None/False — the router then
    # falls back to re-prefill, which is always safe.

    def export_parked_kv(self, session_id: str):
        """A session's parked host-KV entry (``ParkedKV``), stripped of
        device-staged buffers, or None. Peek only: the source keeps
        owning the entry until the migration confirms and calls
        :meth:`drop_parked_kv`."""
        return None

    def import_parked_kv(self, entry) -> bool:
        """Adopt a migrated entry into this engine's host pool. False
        when the entry is refused (shape/tier mismatch, pool disabled,
        over budget) — the refusal leaves the pool untouched."""
        return False

    def drop_parked_kv(self, session_id: str) -> bool:
        """Purge one session's parked entry (migration source cleanup;
        touches ONLY the host pool, so it is safe on a replica whose
        engine thread is down)."""
        return False

    def parked_kv_info(self, session_id: str) -> tuple[int, int] | None:
        """(kept_tokens, nbytes) of a session's parked entry, or None —
        the cheap metadata the migration policy prices before moving
        any bytes."""
        return None


class TPUEngine(EngineBase):
    """The real engine. Owns params, KV cache, tokenizer, decode loop."""

    def __init__(self, model_cfg: ModelConfig, params: Any,
                 tokenizer: Tokenizer, *, num_slots: int = 16,
                 max_len: int = 8192, prefill_chunk: int = 512,
                 dtype: Any = jnp.bfloat16, seed: int = 0,
                 context_window: int | None = None, mesh: Any = None,
                 use_pallas_attention: bool = False,
                 use_pallas_int8: bool = True,
                 weight_quant: str = "off",
                 weight_quant_group: int = 128,
                 use_pallas_int4: bool = False,
                 steps_per_call: int = 8, pipeline_depth: int = 2,
                 sampling_method: str = "fast",
                 spec_decode: str = "off", spec_draft_len: int = 7,
                 spec_breakeven: float = 1.45,
                 shared_prefix: bool = True,
                 queue_bound: int = 256,
                 default_deadline_s: float = 30.0,
                 bulk_aging_s: float = 5.0,
                 kv_host_budget_mb: float | None = None,
                 kv_park_ttl_s: float | None = None,
                 kv_park_idle_s: float | None = None,
                 kv_restore_min_tokens: int | None = None,
                 kv_quant: str = "none",
                 kv_quant_granule: str = "token",
                 kv_layout: str = "dense",
                 kv_block_size: int = 16,
                 kv_pool_blocks: int = 0,
                 kv_reserve_policy: str = "fixed",
                 kv_reserve_tokens: int = 128,
                 kv_radix: bool = False,
                 kv_radix_min_blocks: int = 0,
                 kv_radix_evict_policy: str = "lru",
                 structured: str = "auto",
                 structured_max_states: int = 8192,
                 structured_state_budget: int = 16384,
                 structured_jf_min: int = 4,
                 structured_cache: int = 64,
                 structured_json_depth: int = 3):
        self.cfg = model_cfg
        self.params = params
        self.tokenizer = tokenizer
        self.num_slots = num_slots
        # Cache length rounds up to the bucket granule: the flash prefill
        # (block 512) and the Pallas decode kernel (block 128) both need
        # a divisible key axis, and an off-granule TPU_MAX_MODEL_LEN like
        # 1000 is a legal config. The request-visible limit stays at the
        # configured length via usable_len.
        self.max_len = -(-max_len // _KV_BUCKETS[0]) * _KV_BUCKETS[0]
        self.usable_len = min(max_len, context_window or max_len)
        self.prefill_chunk = min(prefill_chunk, max(_PREFILL_BUCKETS))
        self.dtype = dtype
        self.mesh = mesh
        # GSPMD cannot partition a custom kernel over a mesh; the Pallas
        # paths are single-device optimisations only. The attention and
        # int8-matmul kernels gate independently.
        self.use_pallas_attention = use_pallas_attention and mesh is None
        self.use_pallas_int8 = use_pallas_int8 and mesh is None
        # Int4 weight tier (fasttalk_tpu/quantization/, docs/
        # QUANTIZATION.md): the seven layer matmuls carry nibble-packed
        # {"q4", "s"} leaves and dequantize inside the matmul operand
        # read (ops/quant.py). The compat matrix is EXPLICIT, mirroring
        # the Config checks so library callers get the same named
        # errors: int4 COMPOSES with KV_QUANT=int8, KV_LAYOUT=paged,
        # speculative and structured decoding (all downstream of the
        # logits); it rejects a mesh (the sharded load/init path for
        # packed leaves is unvalidated — the partition rules exist in
        # parallel/sharding.py).
        if weight_quant not in ("off", "int8", "int4"):
            raise ValueError(f"weight_quant must be 'off', 'int8' or "
                             f"'int4', got {weight_quant!r}")
        self.weight_quant = weight_quant
        self.weight_quant_group = int(weight_quant_group)
        if weight_quant == "int4":
            from fasttalk_tpu.quantization.int4 import validate_group

            if mesh is not None:
                raise ValueError(
                    "WEIGHT_QUANT=int4 is single-device only in v1: the "
                    "partition rules for {'q4','s'} leaves exist "
                    "(parallel/sharding.py) but the sharded load/init "
                    "path is unvalidated — set TPU_TP_SIZE=TPU_DP_SIZE="
                    "TPU_SP_SIZE=1")
            validate_group(model_cfg, self.weight_quant_group)
        if use_pallas_int4 and weight_quant != "int4":
            raise ValueError(
                "TPU_USE_PALLAS_INT4=true requires WEIGHT_QUANT=int4 "
                "(the kernel reads nibble-packed {'q4','s'} leaves)")
        self.use_pallas_int4 = (use_pallas_int4 and mesh is None
                                and weight_quant == "int4")
        # Int8 KV-cache tier (ops/kv_quant.py, docs/KVCACHE.md): the
        # cache stores int8 rows + per-row float32 scales; every KV
        # touchpoint (decode scatter, the prefill paths, prefix copy,
        # host park/restore) moves the quantized domain, halving
        # resident HBM, attention-read bandwidth and offload copy
        # bytes. The compatibility matrix is EXPLICIT — unsupported
        # combinations raise here (and at Config validation with the
        # same reasons) rather than silently degrading:
        # - mesh: the scale arrays do not shard with the kv axis yet;
        # - speculative decoding: the spec carry does not thread the
        #   scale arrays through the verify block.
        # The Pallas decode kernel COMPOSES with this tier: int8 rows
        # + scales DMA into VMEM and dequantize inside the kernel
        # (ops/pallas_attention.py).
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be 'none' or 'int8', "
                             f"got {kv_quant!r}")
        self.kv_quant = kv_quant == "int8"
        if self.kv_quant:
            from fasttalk_tpu.ops.kv_quant import granule_dim

            if mesh is not None:
                raise ValueError(
                    "KV_QUANT=int8 is single-device only: the per-row "
                    "scale arrays do not shard with the kv axis yet")
            if spec_decode in ("ngram", "auto"):
                raise ValueError(
                    "KV_QUANT=int8 is incompatible with speculative "
                    "decoding (the spec carry does not thread the "
                    "scale arrays through the verify block) — set "
                    "TPU_SPEC_DECODE=off")
            self.kv_scale_granule = granule_dim(kv_quant_granule,
                                                model_cfg.num_kv_heads)
        else:
            self.kv_scale_granule = 0
        # Extra _note_compile attrs for cache-touching programs: the
        # quantized tier's executables get their own ledger keys, the
        # bf16 tier's keys stay byte-identical to before.
        self._kvq_attrs = {"kv_quant": "int8"} if self.kv_quant else {}
        if self.weight_quant == "int4":
            # Int4 executables get their own ledger keys; the off/int8
            # tiers' keys stay byte-identical to before this tier
            # existed (the acceptance bar for WEIGHT_QUANT=off).
            self._kvq_attrs = dict(self._kvq_attrs,
                                   weight_quant="int4")
        # Paged KV tier (KV_LAYOUT=paged — kvcache/blocks.py,
        # docs/KVCACHE.md "Paged tier"): the cache becomes one flat
        # block pool [L, blocks*block_size, Kv, H] and per-slot block
        # tables map logical positions to pool rows, so HBM admission
        # capacity is priced at blocks actually in use instead of
        # every slot's worst-case context. Composes with the int8
        # tier (scales live in pool layout), the host park/offload
        # tier (block-granular entries), speculative + structured
        # decoding (both ride the scatter decode path), and the
        # Pallas decode kernel (block-walking variant). Single-device
        # only, same precedent as shared_prefix/KV_QUANT: the pool
        # and tables are host-orchestrated per chip.
        if kv_layout not in ("dense", "paged"):
            raise ValueError(f"kv_layout must be 'dense' or 'paged', "
                             f"got {kv_layout!r}")
        self.paged = kv_layout == "paged"
        self.kv_block_size = int(kv_block_size)
        self._kv_blocks: BlockAllocator | None = None
        if self.paged:
            bs = self.kv_block_size
            if bs < 8 or bs > _KV_BUCKETS[0] or bs & (bs - 1):
                raise ValueError(
                    f"KV_BLOCK_SIZE must be a power of two in "
                    f"[8, {_KV_BUCKETS[0]}], got {bs}")
            if mesh is not None:
                raise ValueError(
                    "KV_LAYOUT=paged is single-device only: the block "
                    "pool and per-slot tables are host-orchestrated "
                    "per chip (no tp/dp/sp mesh yet)")
            if kv_reserve_policy not in ("none", "fixed", "max_tokens"):
                raise ValueError(
                    f"kv_reserve_policy must be none|fixed|max_tokens, "
                    f"got {kv_reserve_policy!r}")
            self.kv_reserve_policy = kv_reserve_policy
            self.kv_reserve_tokens = max(0, int(kv_reserve_tokens))
            # 0 = dense-equivalent pool (same HBM as the dense layout;
            # the factory passes a budget-derived count in production).
            self.kv_pool_blocks = int(kv_pool_blocks) \
                or num_slots * self.max_len // bs
            self._kv_blocks = BlockAllocator(self.kv_pool_blocks, bs,
                                             num_slots)
        # Radix-tree automatic prefix cache (kvcache/radix.py,
        # docs/KVCACHE.md "Automatic prefix cache"): retired/parked
        # sessions donate their clean prefix blocks to a radix tree
        # keyed by chained block hashes; every admission aliases the
        # longest cached chain and prefills only the delta. Requires
        # the paged layout (the tree owns pool blocks) — Config
        # enforces the same cross-check with a named startup error.
        if kv_radix and not self.paged:
            raise ValueError(
                "KV_RADIX_ENABLED=true requires KV_LAYOUT=paged (the "
                "radix prefix cache holds device pool blocks; the "
                "dense layout has no block pool)")
        self.kv_radix = bool(kv_radix)
        self._kv_radix: RadixTree | None = None
        if self.kv_radix:
            token_row_bytes = (2 * model_cfg.num_layers
                               * model_cfg.num_kv_heads
                               * model_cfg.head_dim
                               * (1 if kv_quant == "int8"
                                  else jnp.dtype(dtype).itemsize))
            self._kv_radix = RadixTree(
                self._kv_blocks,
                min_free_blocks=max(0, int(kv_radix_min_blocks)),
                evict_policy=kv_radix_evict_policy,
                token_bytes=token_row_bytes)
            self._kv_blocks.set_pressure(self._kv_radix.evict)
        # Worst-case decode-position advances of in-flight calls
        # (paged only): the dispatcher must pre-allocate blocks for
        # where the DEVICE can be, which leads the host mirrors by
        # these.
        self._paged_leads: deque[int] = deque()
        # Single-device decode uses models.llama.forward_decode: the
        # whole cache rides the step scan's CARRY (carries alias inside
        # a program), each step scatter-writes only the new K/V column,
        # and attention reads a slice bounded by the KV bucket. The r2
        # design sliced the bucket out of the cache and scattered it
        # back around every K-step call; together with the scan-ys
        # recycling inside forward() those copies traced at ~40% of
        # decode wall time on a v5e-1 (measured best structure of five:
        # 3.96 ms/step vs 4.99 classic, llama.py forward_decode note).
        # The mesh path keeps forward(): its cache is "sp"-sharded and
        # per-layer dynamic slices would break GSPMD's even sharding.
        self._scatter_decode = mesh is None
        # Which attention path decode steps actually run — perf
        # attribution only (README perf table "kernel" column,
        # BENCH_MODE=roofline): all four decode families (plain/
        # history/fsm/spec) route through forward_decode's
        # pallas_dense/pallas_paged flags on the scatter path.
        if self.use_pallas_attention:
            self.attention_kernel = ("pallas_paged" if self.paged
                                     else "pallas_dense")
        else:
            self.attention_kernel = ("xla_gather" if self.paged
                                     else "xla_dense")
        # Self-drafting speculative decoding (engine-owned, no second
        # model): drafts come from the slot's own token history via
        # on-device prompt-lookup, a verify block of draft+1 positions
        # runs through forward_decode_multi, and the longest
        # sampled-equal prefix is accepted — exactly
        # distribution-preserving for deterministic drafts (sampling
        # t~p and accepting while t == draft emits accept-prob p(d) and
        # the residual distribution on mismatch). Device-side drafting
        # keeps the call pipeline intact: the host is never in the
        # draft loop, so spec calls pipeline exactly like plain ones.
        #
        # Modes: "ngram" = every call speculative; "auto" = the engine
        # decides per call from its own measured acceptance — spec when
        # the EMA tokens-per-verify clears the measured break-even
        # (docs/SPEC_DECODE.md: a verify block costs ~1.43 plain steps
        # on v5e), plain otherwise, with a periodic probe call so a
        # workload shift (e.g. templated text arriving) is noticed.
        # Auto never loses more than the probe overhead (~1 call in
        # 16) and wins whenever drafts are being accepted — VERDICT r4
        # #3's no-knob-guessing mode.
        # Requires the scatter-decode path. Composes with the Pallas
        # attention kernel: the verify block (T = draft+1 positions)
        # runs through the multi-token q generalisation of the kernel
        # (dense and paged variants), so spec no longer forces
        # TPU_USE_PALLAS_ATTENTION off.
        spec_ok = self._scatter_decode
        self.spec_mode = (spec_decode
                          if spec_ok
                          and spec_decode in ("ngram", "auto") else "off")
        self.spec_draft = (max(1, spec_draft_len)
                           if self.spec_mode != "off" else 0)
        self.spec_breakeven = spec_breakeven
        self._spec_probe_every = 16
        self._spec_probe_countdown = 1  # probe on the first call
        # EMA of tokens emitted per verify block: sizes the dispatcher's
        # token promises and drives the auto-mode decision.
        self._spec_ema = 1.0
        # Cross-session shared-prefix KV: a fresh admission whose prompt
        # starts with rows already resident in ANOTHER slot (the
        # common-system-prompt fleet case) copies those rows in HBM
        # instead of re-prefilling them — a [L, plen, Kv, H] device
        # copy is ~free next to recomputing the prefix through the
        # model. Single-device only: on a mesh the slot axis is
        # "dp"-sharded and a cross-slot dynamic slice would bounce
        # through collectives.
        self.shared_prefix = shared_prefix and mesh is None
        # Structured decoding (fasttalk_tpu/structured/,
        # docs/STRUCTURED.md): per-request grammar/JSON-schema
        # constraints compiled to token FSMs whose allowed-token mask
        # is gathered inside the jitted decode scan. The compatibility
        # matrix is EXPLICIT, following the KV-quant precedent:
        # - single-device only in v1 (the mesh decode path is the
        #   non-scatter forward; per-slot FSM state is not threaded
        #   through it);
        # - the Pallas decode kernel composes (it rides the scatter
        #   path via pallas_dense/pallas_paged);
        # - speculative decoding pauses per CALL while any constrained
        #   slot is running (verify-block masking is unvalidated) and
        #   resumes when the last constrained slot finishes.
        # "auto" degrades to unavailable on incompatible engines
        # (constrained REQUESTS are rejected with the reason; plain
        # serving is untouched); "on" makes the incompatibility a
        # construction error; "off" disables the subsystem.
        if structured not in ("auto", "on", "off"):
            raise ValueError(f"structured must be auto|on|off, "
                             f"got {structured!r}")
        reason: str | None = None
        if mesh is not None:
            reason = ("structured decoding is single-device only in "
                      "v1 (no tp/dp/sp mesh — per-slot FSM state is "
                      "not threaded through the sharded decode path)")
        if structured == "on" and reason is not None:
            raise ValueError(f"STRUCTURED_MODE=on: {reason}")
        if structured == "off":
            reason = "disabled (STRUCTURED_MODE=off)"
        # None = constrained requests are served; a string = the
        # rejection reason (serving layers read this pre-breaker).
        self.structured_reason = reason
        self._st_jf_min = max(0, structured_jf_min)
        self._st_cfg = {"max_states": structured_max_states,
                        "state_budget": structured_state_budget,
                        "cache_size": structured_cache,
                        "json_depth": structured_json_depth}
        self._st_compiler: FSMCompiler | None = None   # lazy (asyncio)
        self._st_compiler_lock = threading.Lock()
        self._st_arena: FSMArena | None = None         # lazy (engine)
        self._st_sample_fn: Any = None
        self._st_patch_fn: Any = None

        if mesh is not None:
            # Tensor-parallel serving: weights and KV sharded over ICI;
            # GSPMD turns the row-parallel matmuls into all-reduces.
            # (The reference's only TP story was forwarding
            # --tensor-parallel-size to an external container,
            # docker-compose.vllm.yml:42.) The cache is created directly
            # in its shards; params are re-placed (a no-op when the
            # loader already put them with parallel.sharding.param_put).
            from fasttalk_tpu.parallel.sharding import (shard_params,
                                                        validate_mesh)
            validate_mesh(mesh, num_kv_heads=model_cfg.num_kv_heads,
                          num_heads=model_cfg.num_heads,
                          hidden=model_cfg.hidden_size,
                          intermediate=model_cfg.intermediate_size,
                          vocab=model_cfg.vocab_size,
                          num_slots=num_slots, max_len=self.max_len)
            self.params = shard_params(params, mesh)
        self.cache = self._make_cache()
        self.seed = seed
        # Sampling is restricted to ids the tokenizer can decode: with a
        # real checkpoint the two vocabs match and this is a no-op, but
        # weight-free serving pairs random-init weights (model vocab,
        # e.g. 128256) with the bundled 32k tokenizer — unclamped
        # sampling then emits ~75% undecodable ids, whose empty text
        # deltas hold first-token frames back a whole decode call.
        self.sample_vocab = min(model_cfg.vocab_size,
                                getattr(tokenizer, "vocab_size",
                                        model_cfg.vocab_size))
        # Session KV host-offload tier (docs/KVCACHE.md): a budgeted
        # host-RAM pool parks evicted/idle sessions' kept KV rows so a
        # returning session restores by copy instead of re-prefilling
        # its whole history. Single-device only, like shared_prefix: on
        # a mesh the cache is sharded and a host snapshot would bounce
        # through cross-host collectives. Unset knobs resolve from the
        # KV_* env (Config passes them explicitly in production).
        kvdef = kv_env_defaults()
        budget_mb = kvdef["budget_mb"] if kv_host_budget_mb is None \
            else kv_host_budget_mb
        if mesh is not None:
            budget_mb = 0.0
        self._kv_pool = HostKVPool(
            budget_mb=budget_mb,
            ttl_s=kvdef["ttl_s"] if kv_park_ttl_s is None
            else kv_park_ttl_s)
        self._kv_policy = RestorePolicy(
            min_tokens=int(kvdef["min_tokens"]
                           if kv_restore_min_tokens is None
                           else kv_restore_min_tokens))
        self._kv_offload = KVOffloader(self._kv_pool, self._kv_policy,
                                       tracer=get_tracer())
        self._kv_park_idle_s = kvdef["idle_s"] if kv_park_idle_s is None \
            else kv_park_idle_s
        self._kv_last_tick = 0.0
        self.slots = SlotManager(num_slots, self.max_len,
                                 on_evict=self._park_on_evict,
                                 on_unpin=self._on_slot_unpin)
        self.steps_per_call = max(1, steps_per_call)
        # Burst-mode call length: while admissions or prefills are
        # pending, dispatch SHORT calls so a new arrival's prefill waits
        # behind ~30 ms of in-order device queue instead of
        # pipeline_depth x ~100 ms (long calls amortise the per-call
        # cache boundary copy, which is what steady-state wants; TTFT
        # under concurrent load wants the opposite).
        self.steps_burst = min(8, self.steps_per_call)
        self.pipeline_depth = max(1, pipeline_depth)
        self.sampling_method = sampling_method
        # Device→host copies run on a small worker pool, submitted at
        # dispatch time, so fetches overlap both each other and later
        # calls' compute. On relayed devices every fetch REQUEST costs a
        # full link round trip when it is issued (measured ~105 ms RTT
        # with copy_to_host_async a no-op — serial retirement capped the
        # whole engine at one K-step call per RTT), but concurrent
        # fetches share the trip (8 parallel fetches ≈ 1 RTT,
        # scripts/profile_prefill.py), so retirement only ever waits on
        # the oldest outstanding copy. Workers only read result arrays
        # the engine never mutates; all dispatch stays on the engine
        # thread.
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=max(4, self.pipeline_depth + 2),
            thread_name_prefix="tpu-fetch")
        # Outstanding device→host fetch futures (self._fetch). Tracked
        # independently of _inflight/_pending_firsts because
        # _abort_all clears those deques on a crash — restart() must
        # still be able to QUIESCE the copies before it drops the
        # cache refs (see the restart note).
        self._fetch_pending: set[Future] = set()
        self._reset_decode_state()

        # Multi-host SPMD serving (parallel/spmd_serving.py): when set,
        # every serving-time device call publishes a replay descriptor
        # BEFORE dispatching, so follower processes execute the same
        # program sequence against their shards. Leader-only decision
        # making; followers never start() an engine thread.
        self.call_sink: Any = None

        self._commands: queue.Queue = queue.Queue()
        # Admission control replaces the r1 unbounded FIFO `_waiting`
        # list: bounded queue, priority classes, per-session fairness,
        # deadlines, shed-with-retry_after, graceful drain
        # (scheduling/scheduler.py, docs/SCHEDULING.md). Submissions go
        # straight into the scheduler from the asyncio side (so shed
        # decisions are synchronous); the engine thread pops.
        self._slo = get_slo()
        self._events = get_events()
        self._sched = RequestScheduler(
            queue_bound=queue_bound,
            default_deadline_s=default_deadline_s,
            bulk_aging_s=bulk_aging_s, slots=num_slots,
            # SLO-aware shedding (docs/OBSERVABILITY.md): while the
            # interactive class is page-burning, incoming bulk is shed
            # at the door so capacity goes to the broken promise.
            slo_gate=self._slo.should_shed)
        # Engine-loop heartbeat (observability/watchdog.py): stamped
        # once per loop iteration; a stale stamp with pending work is a
        # hung step (blocked device call) the watchdog turns into a
        # detected, logged, recoverable incident.
        self._hb_mono: float | None = None
        self._prefilling: list[_PrefillState] = []  # long prompts, FIFO
        self._running: dict[int, _Request] = {}  # slot index -> request
        self._by_id: dict[str, _Request] = {}
        self._release_after: set[str] = set()  # sessions to unpin on finish
        self._thread: threading.Thread | None = None
        self._stopped = threading.Event()
        self._started = False
        # Serializes shutdown vs. supervised restart: without it a
        # restart running on an executor thread could observe
        # _started=False mid-shutdown and spawn a fresh engine thread
        # after the process believes the engine is down.
        self._lifecycle_lock = threading.Lock()
        # Serializes terminal-state races between the engine thread
        # (_finish) and the watchdog thread (force_fail): the
        # stall-fail flag set and the SLO recorded-once check must be
        # atomic or a request finishing at the instant it is
        # force-failed double-records its SLO sample.
        self._term_lock = threading.Lock()
        self._closed = False
        self._decode_fns: dict[int, Any] = {}
        self._prefill_fns: dict[int, Any] = {}
        self._spec_fns: dict[tuple, Any] = {}
        self._patch_fn: Any = None
        self._hist_patch_fns: dict[int, Any] = {}
        self._sample_place_fn: Any = None

        m = get_metrics()
        self._m_tokens = m.counter("engine_tokens_generated_total",
                                   "tokens generated by the engine")
        self._m_requests = m.counter("engine_requests_total",
                                     "generation requests accepted")
        self._m_ttft = m.histogram("engine_ttft_ms", "time to first token")
        self._m_step = m.histogram(
            "engine_decode_wait_ms",
            "host blocking wait per retired K-step decode call "
            "(near zero when retirement overlaps the next call)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000, 4000))
        self._m_prefill = m.histogram(
            "engine_prefill_ms", "prefill wall time per request",
            buckets=(4, 16, 64, 256, 1000, 4000, 16000, 60000))
        self._m_active = m.gauge("engine_active_slots", "slots decoding")
        self._m_queue = m.gauge("engine_queue_depth", "requests waiting")
        self._m_prefix = m.counter("engine_prefix_tokens_reused_total",
                                   "prompt tokens served from resident KV")
        self._m_shared = m.counter(
            "engine_shared_prefix_tokens_total",
            "prompt tokens served by cross-slot KV copy instead of "
            "prefill")
        self._m_spec = m.histogram(
            "engine_spec_tokens_per_verify",
            "tokens emitted per speculative verify block (accepted "
            "drafts + 1); 1 means no draft accepted",
            buckets=tuple(range(1, max(2, self.spec_draft + 2))))
        # Structured decoding (docs/STRUCTURED.md): volume, the
        # jump-forward savings (tokens emitted without model steps),
        # and validity-contract violations (must stay 0).
        self._m_st_requests = m.counter(
            "structured_requests_total",
            "constrained (structured-output) generations accepted")
        self._m_st_jump = m.counter(
            "structured_jump_forward_tokens_total",
            "forced tokens emitted by jump-forward without decode "
            "steps")
        # Request-phase histograms (ISSUE 1): where a request's latency
        # lives, as aggregates; the span tracer carries the per-request
        # breakdown.
        self._m_queue_wait = m.histogram(
            "queue_wait_ms",
            "wait from request submit to slot admission")
        self._m_prefill_req = m.histogram(
            "prefill_ms",
            "prefill wall time per request, admission to first-token "
            "sample", buckets=(4, 16, 64, 256, 1000, 4000, 16000, 60000))
        self._m_intertok = m.histogram(
            "inter_token_ms",
            "gap between consecutive tokens of one request",
            buckets=(0.5, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000,
                     4000))
        self._tracer = get_tracer()
        # Journey stamps (observability/journey.py): monotonic marks
        # taken around the blocking device fetch of the CURRENT
        # retirement, attached per-request in _flush_emit when the
        # request opted in. One pair per retirement, not per request.
        self._j_wait0: float = 0.0
        self._j_fetched: float = 0.0
        # Attribution ledger (observability/perf.py): binds the served
        # model's FLOP cost estimate so step records can carry per-call
        # FLOPs and /perf can report achieved-vs-peak MFU. The KV
        # element size feeds the ledger's FLOP/byte and KV-bandwidth
        # figures honestly — int8 rows + scales, never an assumed bf16.
        # Bytes one decode step reads per (slot, position) row across
        # all layers: k+v rows, plus the scale rows when quantized.
        kv_elt = 1 if self.kv_quant else jnp.dtype(dtype).itemsize
        self._kv_row_bytes = 2 * model_cfg.num_layers * (
            model_cfg.num_kv_heads * model_cfg.head_dim * kv_elt
            + self.kv_scale_granule * 4)
        # Weight bytes one decode step streams from HBM: every resident
        # leaf is read once per step — except an UNTIED embedding, which
        # the step only gathers a few rows of (the tied table doubles as
        # the head matmul and is streamed in full). Summing actual leaf
        # nbytes keeps the figure honest per tier: bf16 arrays, int8
        # {"q","s"} and int4 {"q4","s"} dicts alike, scales included.
        def _tree_bytes(t: Any) -> int:
            return int(sum(x.nbytes
                           for x in jax.tree_util.tree_leaves(t)))

        self._weight_bytes_per_step = _tree_bytes(params)
        if "lm_head" in params:
            self._weight_bytes_per_step -= _tree_bytes(params["embed"])
        self._perf = get_perf()
        self._perf.bind_model(model_cfg, num_slots,
                              jnp.dtype(dtype).name,
                              kv_quant=kv_quant,
                              kv_row_bytes=self._kv_row_bytes,
                              weight_quant=self.weight_quant,
                              weight_bytes_per_step=(
                                  self._weight_bytes_per_step),
                              attention_kernel=self.attention_kernel)

    def _make_cache(self) -> KVCache:
        if self.paged:
            return init_paged_cache(
                self.cfg, self.kv_pool_blocks, self.kv_block_size,
                self.dtype, quantized=self.kv_quant,
                scale_granule=max(1, self.kv_scale_granule))
        if self.mesh is None:
            return init_cache(self.cfg, self.num_slots, self.max_len,
                              self.dtype, quantized=self.kv_quant,
                              scale_granule=max(1,
                                                self.kv_scale_granule))
        from jax.sharding import NamedSharding

        from fasttalk_tpu.parallel.sharding import cache_pspecs

        return init_cache(self.cfg, self.num_slots, self.max_len, self.dtype,
                          device=NamedSharding(self.mesh, cache_pspecs().k))

    def _reset_decode_state(self) -> None:
        """(Re)build the host mirrors and device-resident decode state."""
        num_slots = self.num_slots
        # Host mirrors of the per-slot decode state. The authoritative
        # copies live on the device and chain through decode calls; slot
        # changes are scattered onto them with _patch_slot_state.
        self._positions = np.zeros((num_slots,), np.int32)
        self._active_mask = np.zeros((num_slots,), bool)
        self._temps = np.zeros((num_slots,), np.float32)
        self._topks = np.zeros((num_slots,), np.int32)
        self._topps = np.ones((num_slots,), np.float32)
        self._reps = np.ones((num_slots,), np.float32)
        self._press = np.zeros((num_slots,), np.float32)
        self._freqs = np.zeros((num_slots,), np.float32)
        self._cur_tokens = self._put(np.zeros((num_slots,), np.int32))
        self._positions_dev = self._put(self._positions)
        self._active_dev = self._put(self._active_mask)
        self._temps_dev = self._put(self._temps)
        self._topks_dev = self._put(self._topks)
        self._topps_dev = self._put(self._topps)
        self._reps_dev = self._put(self._reps)
        self._press_dev = self._put(self._press)
        self._freqs_dev = self._put(self._freqs)
        # Per-slot emitted-token counts [S, sample_vocab] — the penalty
        # state (ops/sampling.apply_penalties). Maintained in-program by
        # the decode steps (each step counts the token it FEEDS, so every
        # emitted token — including the prefill-sampled first — is
        # counted exactly once); zeroed by the patch program when a slot
        # is (re)admitted or finishes. At [16, 128k] int32 this is ~8 MB.
        self._counts_dev = self._put(
            np.zeros((num_slots, self.sample_vocab), np.int32))
        self._rng_dev = self._put(jax.random.PRNGKey(self.seed))
        # Speculative decoding's device-resident token history
        # [S, max_len]: the draft source. Chained through spec calls
        # (accepted tokens appended in-program); prompt tokens are
        # uploaded at admission via _patch_slot_state. int32, ~KBs.
        self._history_dev = (self._put(
            np.zeros((num_slots, self.max_len), np.int32))
            if self.spec_draft else None)
        # slot index -> prompt token list awaiting history upload.
        self._dirty_history: dict[int, list[int]] = {}
        # Slots whose host mirrors changed since the last device patch.
        # Changes are SCATTERED onto the chained device arrays instead of
        # draining the pipeline and re-uploading everything — admission
        # and completion never stall in-flight decode calls.
        self._dirty_slots: set[int] = set()
        # In-flight decode calls: (host-copy Future, EXPECTED tokens the
        # call will emit per request, EXPECTED positions it advances,
        # the (slot index, request) pairs running at dispatch time,
        # dispatch timestamp for step telemetry, KV bucket length —
        # the attribution ledger's attention-cost horizon).
        # Plain calls emit exactly K tokens (both fields == K);
        # speculative calls emit K..K*(G+1) and both fields are
        # EMA-based estimates — the dispatcher's base/bucket math may
        # therefore transiently under- or over-estimate device
        # positions, which is safe: the in-call act gate masks steps
        # that would overflow the chosen bucket, and retirement re-syncs
        # the host mirrors (one under-productive call worst case; never
        # a correctness issue). Tokens are attributed to the
        # dispatch-time request, never to whoever occupies the slot at
        # retirement — a slot can be re-admitted to a new request while
        # an older call is still in flight.
        self._inflight: deque[
            tuple[Future, float, int, list[tuple[int, _Request]],
                  float, int, str]] = deque()
        # First sampled tokens whose device→host copy is still in
        # flight: (host-copy Future, [(row, slot_index, request), ...]).
        # Admission emits the first token only when the fetch lands, so
        # prefill never blocks the engine thread on a device round trip.
        self._pending_firsts: deque[tuple[Future, list]] = deque()
        # Structured decoding device state (docs/STRUCTURED.md): the
        # per-slot FSM state vector is chained through constrained
        # decode calls exactly like positions; 0 = the FREE state every
        # unconstrained slot sits in. The union tables (masks/cls/next)
        # upload at admission when the arena grows — never per step.
        self._st_state_dev = self._put(np.zeros((num_slots,), np.int32))
        self._st_sel = np.zeros((num_slots,), np.int32)  # host mirror
        self._st_masks_dev: Any = None
        self._st_cls_dev: Any = None
        self._st_nexts_dev: Any = None
        self._st_dirty: set[int] = set()       # slots needing st patch
        self._st_jf_pending: set[str] = set()  # request ids to jump
        if self._st_arena is not None:
            self._st_arena.dirty = True        # restart: re-upload

    # ---------------- public (asyncio side) ----------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._stopped.clear()
        self._thread = threading.Thread(target=self._run, name="tpu-engine",
                                        daemon=True)
        self._thread.start()

    def shutdown(self, timeout_s: float = 30.0) -> None:
        with self._lifecycle_lock:
            self._closed = True
            if self._started:
                self._commands.put(("stop", None))
                if not self._stopped.wait(timeout=timeout_s):
                    # The engine thread is stuck (a wedged device call,
                    # a hung collective): we are about to leak it —
                    # say WHERE it is stuck instead of leaking
                    # silently. sys._current_frames gives the exact
                    # frame the thread is blocked in.
                    self._log_stuck_thread(timeout_s)
                self._started = False
            self._fetch_pool.shutdown(wait=False, cancel_futures=True)
            self._kv_offload.shutdown()
            if self._st_compiler is not None:
                self._st_compiler.shutdown()

    def _log_stuck_thread(self, timeout_s: float) -> None:
        """Shutdown timed out: capture the stuck engine thread's stack
        (sys._current_frames) into the log and a critical event, so
        the leaked thread is a diagnosed incident instead of a silent
        one. faulthandler-style, but scoped to the one thread and
        delivered through the event log the flight recorder bundles."""
        import sys
        import traceback

        thread = self._thread
        stack = ""
        if thread is not None and thread.ident is not None:
            frame = sys._current_frames().get(thread.ident)
            if frame is not None:
                stack = "".join(traceback.format_stack(frame))
        log.critical(
            f"engine thread failed to stop within {timeout_s:.0f}s; "
            f"leaking it. Stuck at:\n{stack or '<thread already gone>'}")
        self._events.emit("engine_shutdown_stuck", severity="critical",
                          timeout_s=timeout_s,
                          stack=stack[-2000:] if stack else "")

    def restart(self) -> bool:
        """Recover from an engine-thread crash: rebuild the device-side
        decode state (the crash may have struck mid-call, leaving the
        donated cache buffer consumed or poisoned) and start a fresh
        thread on the SAME command queue, so requests submitted during
        the outage are served rather than lost. Session KV residency is
        dropped — a session's next turn re-prefills — but the process
        keeps serving, where the reference's only recovery was a
        container restart (docker restart: unless-stopped,
        docker-compose.vllm.yml:14). Compiled executables are kept:
        weights are intact, so nothing needs recompiling."""
        with self._lifecycle_lock:
            if self._closed:
                return False  # shutdown won; never resurrect past it
            if self.call_sink is not None:
                # Restart is leader-local device-state surgery and is
                # not replicated to followers; multi-host recovery is a
                # cluster restart (parallel/spmd_serving.py scope note).
                log.error("engine restart unsupported in multi-host "
                          "SPMD serving mode")
                return False
            if self.check_connection():
                return True
            if self._thread is not None and self._thread.is_alive():
                return False  # still tearing down; try again later
            log.warning("engine restart: rebuilding device decode state")
            # Parked host KV intentionally SURVIVES the restart: the
            # pool holds host memory only, so sessions whose device
            # residency the crash destroyed still restore their kept
            # prefix instead of re-prefilling the whole history —
            # recovery costs one H2D copy per returning session, not
            # O(history) recompute (docs/KVCACHE.md).
            self._events.emit("engine_restart", severity="critical",
                              parked_sessions=len(self._kv_pool))
            # Entries whose requests were terminal-errored by
            # _abort_all must not be re-admitted; entries submitted in
            # the crash race window (after the sweep) survive and the
            # new thread will admit them.
            self._sched.remove_finished()
            self._prefilling.clear()
            self._running.clear()
            self._release_after.clear()
            # Keep registrations of requests submitted in the crash race
            # window (registered after _abort_all's sweep): their queued
            # submit commands survive on the shared command queue and the
            # new thread will admit them — dropping the registration
            # would strand cancel() for those ids. Prune IN PLACE (not a
            # dict rebuild): generate() on the event loop can insert a
            # registration concurrently, and a rebuild would silently
            # drop it (ADVICE r2) — per-key pops never lose an insert.
            for rid in [rid for rid, r in self._by_id.items()
                        if r.finished]:
                self._by_id.pop(rid, None)
            self.slots = SlotManager(self.num_slots, self.max_len,
                                     on_evict=self._park_on_evict,
                                     on_unpin=self._on_slot_unpin)
            if self.paged:
                # The crash may have struck mid-allocation; the pool is
                # rebuilt with the cache (all sessions re-prefill, so
                # no table survives either).
                self._kv_blocks = BlockAllocator(
                    self.kv_pool_blocks, self.kv_block_size,
                    self.num_slots)
                if self._kv_radix is not None:
                    # Cached prefix rows died with the cache: rebuild
                    # the tree empty over the fresh pool (holds in the
                    # old tree point at the discarded allocator).
                    self._kv_radix = RadixTree(
                        self._kv_blocks,
                        min_free_blocks=self._kv_radix.min_free_blocks,
                        evict_policy=self._kv_radix.evict_policy,
                        token_bytes=self._kv_radix.token_bytes)
                    self._kv_blocks.set_pressure(self._kv_radix.evict)
            self._paged_leads.clear()
            # Quiesce the fetch workers FIRST: the crashed thread's
            # in-flight device calls may still be executing on the
            # async dispatch stream with their host copies mid-flight
            # on the fetch pool (_abort_all cleared the deques, not
            # the workers). Dropping the only cache/decode-state refs
            # while the runtime still reads those buffers corrupts
            # the heap (observed: malloc corruption in back-to-back
            # crash→restart chaos drills on the XLA-CPU client).
            # A landed fetch implies its producing call retired on
            # the in-order dispatch stream.
            from concurrent.futures import TimeoutError as _FutTimeout

            for fut in list(self._fetch_pending):
                try:
                    fut.result(timeout=10)
                except _FutTimeout:
                    # The copy is STILL RUNNING: dropping the only
                    # cache/decode-state refs now is exactly the
                    # use-after-free this quiesce prevents. Refuse
                    # this attempt — the supervisor retries (with
                    # backoff), and a permanently wedged copy exhausts
                    # the restart budget into the designed /health-
                    # dead state instead of corrupting the heap.
                    log.error("engine restart aborted: a device->host "
                              "copy is still in flight after 10s")
                    return False
                except Exception:
                    pass  # the copy FAILING is fine; gone is gone
            try:
                # Sync the in-order dispatch stream on the cache chain
                # itself: the last dispatched call's donated-cache
                # output must exist before we drop its only reference.
                jax.block_until_ready(self.cache.k)
            except Exception:
                pass  # a poisoned cache buffer is being replaced anyway
            # Release the old KV cache (and the in-flight refs pinning
            # decode-state arrays) BEFORE allocating the fresh one: on
            # host-side crashes the donated buffer was never consumed,
            # and holding both copies transiently doubles KV HBM — on
            # memory-tight configs the recovery path itself would OOM
            # and the watchdog would re-OOM every probe (ADVICE r2).
            self.cache = None
            self._inflight.clear()
            self._pending_firsts.clear()
            self.cache = self._make_cache()
            self._reset_decode_state()
            self._started = False
            self.start()
            return self.check_connection()

    def warmup(self, level: str = "fast") -> None:
        """Compile hot shapes before serving traffic, so the first users
        never pay the 20-40s XLA compile (the reference's analogue was
        the engine container's multi-minute cold start behind a 300s
        health start_period, docker-compose.vllm.yml:62-67).

        Must run before ``start()`` (single-threaded device access).
        ``fast`` compiles the common chat shapes (~6 executables): the
        first decode KV bucket, batched prefill at the typical prompt
        bucket and the configured chunk for group sizes {1, num_slots},
        plus the single-slot long-prompt path at the full chunk size
        (one long system prompt is common in voice deployments).
        ``full`` adds every decode KV bucket up to max_len and every
        prefill bucket. Warmup
        calls mask their writes (or, for the single-slot path, write
        into a slot region no session has claimed yet), so no later
        request can observe warmup garbage.
        """
        if level in ("off", "", "none"):
            return
        if self._started:
            raise RuntimeError("warmup() must be called before start()")
        if self.call_sink is not None:
            # Warmup calls are not published to followers; multi-host
            # serving compiles lazily on both sides instead.
            raise RuntimeError(
                "warmup is unsupported with a multi-host call sink "
                "attached (set TPU_WARMUP=off)")
        t0 = time.monotonic()
        kv_buckets = [b for b in _KV_BUCKETS if b <= self.max_len] \
            or [self.max_len]
        # Serving picks buckets from _PREFILL_BUCKETS with b >= chunk, so
        # a sub-16 prefill_chunk still lands on the smallest bucket.
        pbuckets = [b for b in _PREFILL_BUCKETS
                    if b <= self.prefill_chunk] or [_PREFILL_BUCKETS[0]]
        if level != "full":
            common = 64 if 64 in pbuckets else pbuckets[0]
            # Include the long-prompt chunk bucket so the fast warmup's
            # single-slot compile below actually triggers.
            chunk_bucket = next((x for x in _PREFILL_BUCKETS
                                 if x >= self.prefill_chunk),
                                _PREFILL_BUCKETS[-1])
            pbuckets = sorted({common, pbuckets[-1], chunk_bucket})
        decode_buckets = kv_buckets if level == "full" else kv_buckets[:1]

        inactive = self._put(np.zeros((self.num_slots,), bool))
        for b in decode_buckets:
            for steps in sorted({self.steps_burst, self.steps_per_call}):
                if self.spec_draft:
                    # Spec modes dispatch the history-maintaining plain
                    # variant (the no-history one is never used).
                    fn = self._get_decode_fn(b, steps, with_history=True)
                    (self.cache, self._history_dev, self._counts_dev,
                     toks, _, _, _) = fn(
                        self.params, self.cache, self._history_dev,
                        self._counts_dev, self._cur_tokens,
                        self._positions_dev, inactive, self._temps_dev,
                        self._topks_dev, self._topps_dev,
                        self._reps_dev, self._press_dev,
                        self._freqs_dev, self._rng_dev,
                        *self._paged_decode_args(b))
                else:
                    fn = self._get_decode_fn(b, steps)
                    self.cache, self._counts_dev, toks, _, _, _ = fn(
                        self.params, self.cache, self._counts_dev,
                        self._cur_tokens, self._positions_dev, inactive,
                        self._temps_dev, self._topks_dev,
                        self._topps_dev, self._reps_dev,
                        self._press_dev, self._freqs_dev, self._rng_dev,
                        *self._paged_decode_args(b))
                jax.block_until_ready(toks)
                if self.spec_draft:
                    # All-inactive spec warmup: every write masks out.
                    # No eligibility gate here — dispatch eligibility
                    # depends on runtime positions (EMA-sized need),
                    # so any gate that skips a (bucket, steps) pair
                    # warmup-time can still see it requested mid-stream
                    # and pay the compile under traffic.
                    sfn = self._get_spec_decode_fn(b, steps)
                    (self.cache, self._history_dev, self._counts_dev,
                     toks, _, _, _) = sfn(
                        self.params, self.cache, self._history_dev,
                        self._counts_dev, self._cur_tokens,
                        self._positions_dev, inactive,
                        self._temps_dev, self._topks_dev,
                        self._topps_dev, self._reps_dev, self._press_dev,
                        self._freqs_dev, self._rng_dev,
                        *self._paged_decode_args(b))
                    jax.block_until_ready(toks)
        if self.spec_draft:
            # The admission-path history upload (slot indices out of
            # range: every row drops). 256 is the common chat-prompt
            # row bucket; longer prompts compile their bucket on first
            # use (a tiny pad+scatter program).
            self._history_dev = self._get_hist_patch_fn(
                min(256, self.max_len))(
                self._history_dev,
                self._arg(np.zeros((self.num_slots,
                                    min(256, self.max_len)), np.int32)),
                self._arg(np.full((self.num_slots,), self.num_slots,
                                  np.int32)))
            jax.block_until_ready(self._history_dev)
        # The admission-path helper programs (slot-state patch; they are
        # tiny but a first-request compile is still seconds).
        nopatch = np.zeros((self.num_slots, 9), np.float32)
        (self._counts_dev, self._positions_dev, self._active_dev,
         self._temps_dev, self._topks_dev, self._topps_dev,
         self._reps_dev, self._press_dev, self._freqs_dev) = \
            self._get_patch_fn()(
                self._arg(nopatch), self._counts_dev, self._positions_dev,
                self._active_dev, self._temps_dev, self._topks_dev,
                self._topps_dev, self._reps_dev, self._press_dev,
                self._freqs_dev)

        # The single-slot long-prompt path buckets by the smallest
        # _PREFILL_BUCKETS entry covering a full chunk — warm exactly
        # that shape (pbuckets[-1] only equals it when prefill_chunk is
        # itself a bucket value).
        long_bucket = next((x for x in _PREFILL_BUCKETS
                            if x >= self.prefill_chunk), _PREFILL_BUCKETS[-1])
        for b in pbuckets:
            # Must match the ctx _prefill_group derives for a fresh
            # session (starts=0): the smallest KV bucket covering b.
            ctx = next((k for k in kv_buckets if k >= b), self.max_len)
            for gp in sorted({1, self.num_slots}):
                # All rows masked + out-of-range scatter: no cache (or
                # cur-token) writes. Args are built exactly as the
                # serving path builds them (numpy via _arg) so the
                # compiled executable keys on the same avals.
                rowcfg = np.zeros((gp, 7), np.float32)
                rowcfg[:, 0] = np.arange(self.num_slots,
                                         self.num_slots + gp)
                rowcfg[:, 4:] = (1.0, 40, 0.9)
                if self.paged:
                    fn = self._get_paged_batched_prefill_fn(b, gp, ctx)
                    widx = np.stack([self._paged_oob_indices(j, b)
                                     for j in range(gp)])
                    (self.cache, firsts, self._cur_tokens,
                     self._rng_dev) = fn(
                        self.params, self.cache,
                        self._arg(np.zeros((gp, b), np.int32)),
                        self._arg(rowcfg),
                        self._arg(np.zeros((gp, ctx), np.int32)),
                        self._arg(widx), self._cur_tokens,
                        self._rng_dev)
                else:
                    fn = self._get_batched_prefill_fn(b, gp, ctx)
                    (self.cache, firsts, self._cur_tokens,
                     self._rng_dev) = fn(
                        self.params, self.cache,
                        self._arg(np.zeros((gp, b), np.int32)),
                        self._arg(rowcfg), self._cur_tokens,
                        self._rng_dev)
                jax.block_until_ready(firsts)
            if level == "full" or b == long_bucket:
                # Single-slot long-prompt path: writes land in slot 0's
                # region, unclaimed at warmup time (kv_written stays 0,
                # so nothing ever trusts them). Its first-token sample
                # runs the same jitted sample-and-place program the
                # serving path uses (slot index out of range: the
                # current-token scatter drops).
                if self.paged:
                    wctx = next((k for k in kv_buckets if k >= b),
                                self.max_len)
                    fn = self._get_paged_prefill_fn(b, wctx)
                    self.cache, last = fn(
                        self.params, self.cache,
                        self._arg(np.zeros((b,), np.int32)),
                        np.int32(0),
                        self._arg(np.zeros((wctx,), np.int32)),
                        self._arg(self._paged_oob_indices(0, b)),
                        np.int32(b - 1))
                else:
                    fn = self._get_prefill_fn(b)
                    self.cache, last = fn(
                        self.params, self.cache,
                        self._arg(np.zeros((b,), np.int32)),
                        np.int32(0), np.int32(0), np.int32(b - 1))
                cfg_row = np.array([self.num_slots, 1.0, 40, 0.9],
                                   np.float32)
                first, self._cur_tokens, self._rng_dev = \
                    self._get_sample_place_fn()(
                        last, self._cur_tokens, self._rng_dev,
                        self._arg(cfg_row))
                jax.block_until_ready(first)
        if self._kv_pool.enabled:
            # Host-offload copy programs (kvcache/offload.py): compile
            # every power-of-two bucket now, so no park/restore ever
            # pays a mid-traffic compile stall (the shapes are trivial
            # slice/update programs — cheap next to the model graphs
            # above). The warmup restore writes zero rows into slot 0,
            # which nothing has claimed yet (kv_written stays 0).
            b = max(16, self.kv_block_size) if self.paged else 16
            while True:
                # Slice returns (k, v) — or (k, v, k_scale, v_scale)
                # on the quantized tier — in exactly the restore fn's
                # argument order, so the round trip is layout-agnostic.
                if self.paged:
                    # Gather pool row 0, scatter to dropped OOR rows:
                    # the paged copy programs compile with no writes.
                    rows = self._get_paged_kv_slice_fn(b)(
                        self.cache,
                        self._arg(np.zeros((b,), np.int32)))
                    self.cache = self._get_paged_kv_restore_fn(b)(
                        self.cache, *rows,
                        self._arg(self._paged_oob_indices(0, b)))
                else:
                    rows = self._get_kv_slice_fn(b)(
                        self.cache, np.int32(0))
                    self.cache = self._get_kv_restore_fn(b)(
                        self.cache, *rows, np.int32(0))
                jax.block_until_ready(self.cache.k)
                if b >= self.max_len:
                    break
                b = min(b * 2, self.max_len)
        if self.shared_prefix:
            # Shared-prefix stamp programs at the common granules (the
            # quantized tier's variants copy rows + scales): a cold
            # fleet burst's first admission should not pay this compile
            # on the TTFT path. Src == dst == slot 0 (unclaimed at
            # warmup; kv_written stays 0, so nothing trusts the rows).
            # Paged tier: sharing is block ALIASING (host bookkeeping,
            # nothing to compile) — only the single COW block-copy
            # program warms, src == dst == block 0.
            if self.paged:
                self.cache = self._get_block_copy_fn()(
                    self.cache, np.int32(0), np.int32(0))
            else:
                for plen in {g for g in (64, 256) if g <= self.max_len}:
                    self.cache = self._get_prefix_copy_fn(plen)(
                        self.cache, np.int32(0), np.int32(0),
                        np.int32(0))
            jax.block_until_ready(self.cache.k)
        jax.block_until_ready(self.cache.k)
        # Warm every fetch worker's first device→host copy: on relayed
        # attach paths a thread's FIRST fetch pays one-time client
        # setup well beyond the steady RTT, and without this the first
        # real generation absorbed it as multi-second TTFT.
        futs = [self._fetch_pool.submit(np.asarray, self._cur_tokens)
                for _ in range(self._fetch_pool._max_workers)]
        for f in futs:
            f.result()
        log.info(f"warmup({level}) compiled "
                 f"{len(self._decode_fns) + len(self._prefill_fns)} "
                 f"executables in {time.monotonic() - t0:.1f}s")

    async def generate(self, request_id: str, session_id: str,
                       messages: list[dict], params: GenerationParams,
                       ) -> AsyncGenerator[dict, None]:
        """Stream events: {"type": "token", "text": ...} per delta, then a
        terminal {"type": "done"|"error"|"cancelled", ...}."""
        if not self.check_connection():
            raise LLMServiceError("Engine is not running (call start())",
                                  category=ErrorCategory.CONNECTION,
                                  recoverable=True)
        if self.role == "prefill" and not params.prefill_only:
            # Disaggregated prefill tier: this replica exists to run
            # long prefills with zero decode-slot occupancy — a decode
            # stream admitted here would recreate exactly the
            # interference the role split removes. The router never
            # places normal streams here; this is the engine-side
            # guarantee behind that.
            raise LLMServiceError(
                "replica role is 'prefill': decode streams are "
                "rejected (only prefill_only handoff requests admit)",
                category=ErrorCategory.VALIDATION, recoverable=False)
        if params.prefill_only and not self._kv_pool.enabled:
            raise LLMServiceError(
                "prefill_only requires the host KV pool "
                "(KV_HOST_BUDGET_MB > 0): the finished prefill is "
                "parked there for the decode-tier handoff",
                category=ErrorCategory.VALIDATION, recoverable=False)
        if params.raw_prompt:
            # Raw text-completion path (/v1/completions): BOS + verbatim
            # tokens, no chat template (matching vLLM's completions
            # endpoint, which prepends BOS by default).
            prompt = self.tokenizer.encode_prompt(raw_prompt_text(messages))
        else:
            prompt = self.tokenizer.apply_chat_template(messages)
        if len(prompt) >= self.usable_len:
            raise LLMServiceError(
                f"Prompt of {len(prompt)} tokens exceeds context window "
                f"{self.usable_len}", category=ErrorCategory.VALIDATION,
                recoverable=False)
        req = _Request(
            request_id=request_id, session_id=session_id,
            prompt_tokens=prompt, params=params,
            out_queue=asyncio.Queue(), loop=asyncio.get_running_loop(),
            detok=StreamDetokenizer(self.tokenizer))
        if params.structured is not None:
            # Compile (or cache-hit) the token FSM OFF the engine
            # thread and off this event loop, before submission —
            # admission never blocks on a cold schema. Compat and
            # compile failures are client-shape errors: 400 /
            # invalid_config, never a 500 or a breaker hit.
            if self.structured_reason is not None:
                raise LLMServiceError(
                    f"structured output unavailable: "
                    f"{self.structured_reason}",
                    category=ErrorCategory.VALIDATION,
                    recoverable=False)
            if self.call_sink is not None:
                raise LLMServiceError(
                    "structured output is unsupported in multi-host "
                    "SPMD serving mode",
                    category=ErrorCategory.VALIDATION,
                    recoverable=False)
            t0c = time.monotonic()
            try:
                req.fsm = await self._get_st_compiler().compile_async(
                    params.structured)
            except (StructuredError, FSMTooLarge) as e:
                raise LLMServiceError(
                    str(e), category=ErrorCategory.VALIDATION,
                    recoverable=False) from e
            req.fsm_state = req.fsm.start
            self._m_st_requests.inc()
            if self._tracer.enabled:
                self._tracer.add_span(
                    request_id, "fsm_compile", t0c, time.monotonic(),
                    kind=params.structured.get("kind"),
                    states=req.fsm.n_states,
                    classes=req.fsm.n_classes)
        self._m_requests.inc()
        # Trace the request's whole lifecycle. The serving layer starts
        # the trace first (it owns the ws_send spans and the finish);
        # start() returns True only for engine-seam callers (tests,
        # BENCH_MODE=engine), who then own the finish here.
        trace_owned = self._tracer.start(request_id, session_id)
        if self._tracer.enabled:
            self._tracer.set_phase(request_id, "queued",
                                   priority=params.priority)
        # Register before enqueueing so an immediate cancel() can't race
        # the engine thread's command drain.
        self._by_id[request_id] = req
        try:
            # Admission control: bounded queue, deadline-aware,
            # drain-aware. A shed raises AdmissionRejected (with
            # retry_after) synchronously — the caller gets a terminal
            # signal immediately instead of queueing to time out. A
            # session with a parked host-KV entry will skip most of its
            # prefill at admission — the scheduler's wait estimate gets
            # that saving as a discount so the wait_too_long shed
            # doesn't turn away requests the restore makes cheap.
            self._sched.submit(request_id, session_id,
                               priority=params.priority,
                               deadline_s=params.deadline_s, payload=req,
                               wait_discount_s=self._kv_wait_discount(
                                   session_id, prompt)
                               - self._paged_wait_penalty(len(prompt)))
        except AdmissionRejected:
            self._by_id.pop(request_id, None)
            req.finished = True
            self._slo.record_shed(params.priority)
            if self._tracer.enabled:
                self._tracer.event(request_id, "shed")
            if trace_owned:
                self._tracer.finish(request_id)
            raise
        if self._kv_pool.enabled:
            # Best-effort: pre-upload this session's parked KV rows to
            # the device on the copy thread while the request waits in
            # the queue, so the restore at admission dispatches against
            # device-resident arrays (no H2D on the admission path).
            self._kv_offload.prestage(session_id)
        self._commands.put(("kick", None))  # wake the engine thread
        terminal = False
        try:
            while True:
                event = await req.out_queue.get()
                if event["type"] in ("done", "error", "cancelled"):
                    terminal = True
                yield event
                if terminal:
                    return
        finally:
            if not terminal:
                # Caller abandoned the stream (e.g. WebSocket dropped):
                # free the slot instead of decoding to max_tokens.
                self.cancel(request_id)
            if trace_owned:
                self._tracer.finish(request_id)

    def cancel(self, request_id: str) -> bool:
        req = self._by_id.get(request_id)
        if req is None:
            return False
        req.cancelled = True  # visible to the engine thread immediately
        self._commands.put(("cancel", request_id))
        return True

    def release_session(self, session_id: str) -> None:
        self._commands.put(("release", session_id))

    def begin_drain(self) -> None:
        """Stop admitting new submissions (they shed with retry_after);
        queued and in-flight requests run to completion. Used by server
        shutdown so a rolling restart finishes its users' sentences."""
        self._sched.begin_drain()
        if self._started:
            self._commands.put(("kick", None))

    def pending_requests(self) -> int:
        """Requests not yet terminal (queued + prefilling + running):
        the drain loop polls this toward zero."""
        return len(self._by_id)

    def set_trace_component(self, component: str) -> None:
        """Tag this engine's spans with a fleet component name: in-proc
        replicas of a BENCH_MODE=fleet router share ONE process tracer,
        so the component attr is what keeps replica A's prefill/decode
        spans distinguishable from replica B's in a stitched trace."""
        self._tracer = get_tracer().scoped(component)

    def scheduler_debug(self) -> dict:
        """Scheduler state + queued entries (position, priority,
        remaining deadline) + parked host-KV sessions for the
        monitoring port's /debug/requests."""
        return {"stats": self._sched.stats(),
                "queued": self._sched.snapshot(),
                "kv_host": self._kv_pool.stats(),
                "parked_sessions": self._kv_pool.snapshot()}

    # ---------------- watchdog surfaces (observability/watchdog.py) ----

    def heartbeat_age(self, now: float | None = None) -> float | None:
        """Seconds since the engine loop last completed an iteration
        (None before the first one). A large age with pending work
        means the thread is blocked inside a device call."""
        hb = self._hb_mono
        if hb is None:
            return None
        return (time.monotonic() if now is None else now) - hb

    def progress_report(self, now: float | None = None,
                        ) -> list[dict[str, Any]]:
        """Admitted, unfinished requests with how long each has gone
        without forward progress (a token, a prefill chunk, or
        activation). Queued requests are excluded — the scheduler's
        deadline sweep already governs them."""
        now = time.monotonic() if now is None else now
        out: list[dict[str, Any]] = []
        # list() over the dict's values is atomic under the GIL; the
        # engine thread may mutate the dict but never the snapshot.
        for req in list(self._by_id.values()):
            if req.finished or req.admitted_at is None:
                continue
            last = max(filter(None, (req.last_token_at,
                                     req.last_progress_at,
                                     req.admitted_at)))
            out.append({
                "request_id": req.request_id,
                "session_id": req.session_id,
                "phase": "decode" if req.decode_started_at is not None
                else "prefill",
                "no_progress_s": round(now - last, 3),
            })
        return out

    def force_fail(self, request_id: str, error: str,
                   code: str = "stalled") -> bool:
        """Watchdog termination: emit a terminal error frame NOW, from
        outside the engine thread — the whole point is that the engine
        thread may be hung and unable to process a normal cancel. The
        request is also marked cancelled and a cancel command queued,
        so a revived engine thread frees the slot through the ordinary
        _finish path (whose terminal event lands in an already-closed
        stream and is dropped)."""
        req = self._by_id.get(request_id)
        if req is None:
            return False
        with self._term_lock:
            if req.finished or req.stall_failed:
                return False
            req.stall_failed = True
            req.cancelled = True
        self._record_slo(req, ok=False)
        self._emit(req, {"type": "error", "error": error, "code": code})
        self._commands.put(("cancel", request_id))
        return True

    def _record_slo(self, req: _Request, ok: bool) -> None:
        """Feed one finished request into the SLO engine (idempotent —
        the watchdog's force_fail and the engine's _finish can both
        reach a request, from different threads; the terminal lock
        makes the recorded-once check atomic)."""
        with self._term_lock:
            if req.slo_recorded:
                return
            req.slo_recorded = True
        ttft_ms = ((req.first_token_at - req.submitted_at) * 1000.0
                   if req.first_token_at is not None else None)
        qw_ms = ((req.admitted_at - req.submitted_at) * 1000.0
                 if req.admitted_at is not None else None)
        # A single-token reply has no inter-token gap to judge.
        gap_ms = req.max_gap_ms if req.generated >= 2 else None
        self._slo.record_request(req.params.priority, ok=ok,
                                 ttft_ms=ttft_ms, queue_wait_ms=qw_ms,
                                 max_gap_ms=gap_ms)

    def check_connection(self) -> bool:
        return self._started and self._thread is not None \
            and self._thread.is_alive()

    def get_model_info(self) -> dict:
        return {
            "model": self.cfg.name,
            "vocab_size": self.cfg.vocab_size,
            "num_layers": self.cfg.num_layers,
            "hidden_size": self.cfg.hidden_size,
            "parameters": self.cfg.param_count(),
            "context_window": self.usable_len,
            "decode_slots": self.num_slots,
            "dtype": jnp.dtype(self.dtype).name,
            "kv_quant": "int8" if self.kv_quant else "none",
            "kv_layout": "paged" if self.paged else "dense",
            "weight_quant": self.weight_quant,
            "devices": [str(d) for d in jax.devices()],
            "mesh": dict(self.mesh.shape) if self.mesh is not None else None,
        }

    def get_stats(self) -> dict:
        structured: dict[str, Any] = {
            "available": self.structured_reason is None,
        }
        if self.structured_reason is not None:
            structured["reason"] = self.structured_reason
        if self._st_compiler is not None:
            structured["compiler"] = self._st_compiler.stats()
        if self._st_arena is not None:
            structured["arena"] = self._st_arena.stats()
        out = {
            "slots": self.slots.stats(),
            "waiting": len(self._sched),
            "scheduler": self._sched.stats(),
            "running": len(self._running),
            "kv_quant": "int8" if self.kv_quant else "none",
            "kv_layout": "paged" if self.paged else "dense",
            "kv_host": {**self._kv_pool.stats(),
                        "policy": self._kv_policy.stats()},
            "structured": structured,
        }
        if self.paged:
            used = sum(min(s.kv_written, len(s.tokens))
                       for s in self.slots.slots)
            out["kv_blocks"] = self._kv_blocks.stats(used_tokens=used)
        if self._kv_radix is not None:
            out["kv_radix"] = self._kv_radix.stats()
        return out

    # ---------------- jitted steps ----------------

    def _sink(self, kind: str, **payload) -> None:
        """Publish a device-call replay descriptor to the attached
        multi-host call sink (no-op single-host)."""
        if self.call_sink is not None:
            self.call_sink(kind, payload)

    def _note_compile(self, kind: str, **attrs: Any) -> None:
        """A jitted-executable cache miss while serving traffic is a
        latency incident (the compile stalls the engine thread for
        seconds): record it in the event log. Warmup misses (before
        start()) are the expected cost and are not events — but every
        miss lands in the perf ledger's compile table either way, so
        /perf answers "which shapes compiled, and when"."""
        self._perf.note_compile(kind, serving=self._started, **attrs)
        if self._started:
            self._events.emit("recompile", severity="warning",
                              what=kind, **attrs)

    # Program keys for the perf ledger's per-program device-time
    # attribution: every step record carries the SAME executable key
    # its dispatch's _note_compile would build, so /perf's programs
    # block and compile table join exactly (perf.program_key docs).

    def _decode_program(self, kv_len: int, steps: int,
                        st_on: bool) -> str:
        return program_key(
            "decode", kv_len=kv_len, steps=steps,
            **({"structured": True} if st_on else {}),
            **self._kvq_attrs,
            **({"kv_layout": "paged"} if self.paged else {}))

    def _prefill_program(self, start: int, bucket: int) -> str:
        """The executable key _run_chunk_prefill(start, bucket) routes
        to — the paged ctx computation is duplicated deliberately so
        callers can stamp BEFORE dispatch mutates their state."""
        if self.paged:
            ctx = next((b for b in _KV_BUCKETS
                        if b >= start + bucket and b <= self.max_len),
                       self.max_len)
            return program_key("prefill", chunk=bucket, ctx=ctx,
                               kv_layout="paged", **self._kvq_attrs)
        return program_key("prefill", chunk=bucket, **self._kvq_attrs)

    def _fetch(self, arr) -> Future:
        """Submit a device→host copy on the fetch pool, tracked so
        restart() can wait for every outstanding copy to land before
        rebuilding device state."""
        fut = self._fetch_pool.submit(np.asarray, arr)
        self._fetch_pending.add(fut)
        fut.add_done_callback(self._fetch_pending.discard)
        return fut

    def _put(self, arr):
        """Host array (or PRNG key) → device, replicated over the mesh
        when present."""
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(arr, NamedSharding(self.mesh, PartitionSpec()))

    def _arg(self, arr):
        """Host array destined to be a jitted-call argument. Without a
        mesh the numpy array is passed as-is — the call's own transfer
        is one dispatch, where an explicit device_put costs a separate
        ~ms-scale round trip per array on relayed devices. With a mesh,
        explicit replicated placement is required."""
        return arr if self.mesh is None else self._put(arr)

    def _replicate_sharding(self):
        """Fully-replicated NamedSharding on the mesh (None when single
        device): constrains host-fetched program outputs so every host
        of a multi-process (DCN) mesh can read them."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def _get_decode_fn(self, kv_len: int, steps: int | None = None,
                       with_history: bool = False,
                       with_fsm: bool = False):
        """K decode steps in one jitted call (K = ``steps``, default
        steps_per_call; the dispatcher also compiles the short
        ``steps_burst`` variant for admission-latency-sensitive moments).
        ``with_history`` (auto-spec mode) additionally maintains the
        speculative history buffer so probe calls draft from fresh text.

        The whole per-slot decode state is threaded through the call so
        nothing round-trips to the host between steps: carry = (sliced
        K/V, current token, positions, rng). Returns all K sampled
        tokens; the host consumes them at retirement (SURVEY.md §7 hard
        part #3 — the naive per-step blocking get this replaces
        serialised device and host work).
        """
        steps = self.steps_per_call if steps is None else steps
        sp = self.mesh.shape.get("sp", 1) if self.mesh is not None else 1
        if sp > 1:
            # The sp path attends the FULL sp-sharded cache through
            # decode_attention_sharded (per-chip O(S/sp) folds + a
            # statistics psum — masking bounds the horizon, so KV-
            # bucket specialisation buys nothing); one executable per
            # step count.
            kv_len = self.max_len
        fn = self._decode_fns.get((kv_len, steps, with_history,
                                   with_fsm))
        if fn is not None:
            return fn
        self._note_compile("decode", kv_len=kv_len, steps=steps,
                           **({"structured": True} if with_fsm else {}),
                           **self._kvq_attrs,
                           **({"kv_layout": "paged"} if self.paged
                              else {}))
        # BOTH kernel variants ride the scatter path now
        # (forward_decode routes pallas_dense/pallas_paged), so the
        # kernel composes with everything the scatter family carries:
        # int8 KV, history/spec, structured. The dense kernel needs the
        # bucket divisible by its 128 block — true for the
        # power-of-two >= 512 buckets, false only for a short max_len
        # fallback bucket, which keeps the XLA read.
        use_pallas = self.use_pallas_attention and kv_len % 128 == 0
        scatter = self._scatter_decode
        pallas_paged = self.paged and self.use_pallas_attention
        pallas_dense = use_pallas and not self.paged and scatter
        bsz = self.kv_block_size
        rows = jnp.arange(self.num_slots)
        max_len = self.max_len
        replicate = self._replicate_sharding()
        if with_fsm:
            # Constrained variant (docs/STRUCTURED.md): identical step
            # math plus (1) a per-slot allowed-token mask gathered from
            # the packed-bitmask union table by FSM state and applied
            # to the penalised logits BEFORE candidate preselection —
            # composing with penalties/top-k/top-p exactly like a
            # penalty — and (2) the state advance, a two-gather chain
            # next = nexts[state, cls[sel, token]], all device-
            # resident: no host sync anywhere on the step path.
            # Unconstrained slots ride along in the FREE state (mask
            # all-ones, self-loop). Dispatched only while a constrained
            # slot is running, so plain serving keeps its executables
            # byte-identical. Single-device scatter path only (the
            # engine rejects constrained requests otherwise).
            assert scatter, "structured decode requires the scatter path"
            fn = self._build_fsm_decode(kv_len, steps, with_history,
                                        rows, max_len)
            self._decode_fns[(kv_len, steps, with_history,
                              with_fsm)] = fn
            return fn
        cache_override = None
        if sp > 1:
            from fasttalk_tpu.parallel.ring_attention import \
                decode_attention_sharded

            mesh = self.mesh

            def cache_override(q, ck, cv, positions):  # noqa: F811
                return decode_attention_sharded(q, ck, cv, positions,
                                                mesh)

        if with_history:
            # Auto-spec plain call: identical decode, plus maintaining
            # the spec history invariant (history[s, pos] = fed token)
            # so a later probe/spec call drafts from fresh text.
            assert scatter

            @partial(jax.jit, donate_argnums=(1, 2, 3))
            def decode_call_hist(params, cache: KVCache, history, counts,
                                 cur_tokens, positions, active, temps,
                                 topks, topps, reps, press, freqs, rng,
                                 bt=None):
                def step(carry, _):
                    ck, cv, ks, vs, hist, cnt, cur, pos, key = carry
                    key, sub = jax.random.split(key)
                    act = jnp.logical_and(active, pos < kv_len)
                    wp = jnp.where(act, pos, max_len)
                    hist = hist.at[rows, wp].set(cur, mode="drop",
                                                 unique_indices=True)
                    cnt = cnt.at[rows, cur].add(act.astype(jnp.int32),
                                                unique_indices=True)
                    logits, newc = forward_decode(
                        params, self.cfg, cur, pos,
                        KVCache(ck, cv, ks, vs), act,
                        attn_len=kv_len,
                        pallas_int8=self.use_pallas_int8,
                        pallas_int4=self.use_pallas_int4,
                        block_table=bt, block_size=bsz,
                        pallas_paged=pallas_paged,
                        pallas_dense=pallas_dense)
                    lg = apply_penalties(logits[:, :self.sample_vocab],
                                         cnt, reps, press, freqs)
                    nxt = sample_tokens(lg, sub, temps, topks, topps,
                                        method=self.sampling_method)
                    pos = pos + act.astype(pos.dtype)
                    return (newc.k, newc.v, newc.k_scale, newc.v_scale,
                            hist, cnt, nxt, pos, key), nxt

                (ck, cv, ks, vs, hist, cnt, cur, pos, rng), toks = \
                    jax.lax.scan(
                        step, (cache.k, cache.v, cache.k_scale,
                               cache.v_scale, history, counts,
                               cur_tokens, positions, rng), None,
                        length=steps)
                return KVCache(ck, cv, ks, vs), hist, cnt, toks, cur, \
                    pos, rng

            self._decode_fns[(kv_len, steps, with_history, False)] = \
                decode_call_hist
            return decode_call_hist

        @partial(jax.jit, donate_argnums=(1, 2))
        def decode_call(params, cache: KVCache, counts, cur_tokens,
                        positions, active, temps, topks, topps,
                        reps, press, freqs, rng, bt=None):
            if scatter:
                def step(carry, _):
                    ck, cv, ks, vs, cnt, cur, pos, key = carry
                    key, sub = jax.random.split(key)
                    # A slot that finished mid-pipeline keeps "decoding"
                    # until the host reconciles; clamp it off the
                    # attention horizon so its garbage writes can never
                    # clobber live rows.
                    act = jnp.logical_and(active, pos < kv_len)
                    # Count the token being FED (it was emitted last
                    # step or by prefill), so the penalty at sampling
                    # time covers every emitted token exactly once.
                    cnt = cnt.at[rows, cur].add(act.astype(jnp.int32),
                                                unique_indices=True)
                    logits, newc = forward_decode(
                        params, self.cfg, cur, pos,
                        KVCache(ck, cv, ks, vs), act,
                        attn_len=kv_len,
                        pallas_int8=self.use_pallas_int8,
                        pallas_int4=self.use_pallas_int4,
                        block_table=bt, block_size=bsz,
                        pallas_paged=pallas_paged,
                        pallas_dense=pallas_dense)
                    lg = apply_penalties(logits[:, :self.sample_vocab],
                                         cnt, reps, press, freqs)
                    nxt = sample_tokens(lg, sub, temps, topks, topps,
                                        method=self.sampling_method)
                    pos = pos + act.astype(pos.dtype)
                    return (newc.k, newc.v, newc.k_scale, newc.v_scale,
                            cnt, nxt, pos, key), nxt

                (ck, cv, ks, vs, cnt, cur, pos, rng), toks = \
                    jax.lax.scan(
                        step, (cache.k, cache.v, cache.k_scale,
                               cache.v_scale, counts, cur_tokens,
                               positions, rng), None, length=steps)
                return KVCache(ck, cv, ks, vs), cnt, toks, cur, pos, rng

            ck = jax.lax.slice_in_dim(cache.k, 0, kv_len, axis=2)
            cv = jax.lax.slice_in_dim(cache.v, 0, kv_len, axis=2)

            def step(carry, _):
                sk, sv, cnt, cur, pos, key = carry
                key, sub = jax.random.split(key)
                act = jnp.logical_and(active, pos < kv_len)
                cnt = cnt.at[rows, cur].add(act.astype(jnp.int32),
                                            unique_indices=True)
                logits, small = forward(
                    params, self.cfg, cur[:, None], pos[:, None],
                    KVCache(sk, sv), pos, write_mask=act,
                    pallas_decode=use_pallas,
                    pallas_int8=self.use_pallas_int8,
                    pallas_int4=self.use_pallas_int4,
                    cache_attn_override=cache_override)
                lg = apply_penalties(logits[:, -1, :self.sample_vocab],
                                     cnt, reps, press, freqs)
                nxt = sample_tokens(lg, sub, temps, topks, topps,
                                    method=self.sampling_method)
                pos = pos + act.astype(pos.dtype)
                return (small.k, small.v, cnt, nxt, pos, key), nxt

            (ck, cv, cnt, cur, pos, rng), toks = jax.lax.scan(
                step, (ck, cv, counts, cur_tokens, positions, rng), None,
                length=steps)
            new_k = jax.lax.dynamic_update_slice_in_dim(
                cache.k, ck, 0, axis=2)
            new_v = jax.lax.dynamic_update_slice_in_dim(
                cache.v, cv, 0, axis=2)
            # Sampled tokens leave the program fully replicated: on a
            # multi-host (DCN) mesh a host can only fetch an array whose
            # addressable shards cover it — and [K, S] ints are nothing
            # next to the batch all-reduces GSPMD already inserted.
            if replicate is not None:
                toks = jax.lax.with_sharding_constraint(toks, replicate)
            return KVCache(new_k, new_v), cnt, toks, cur, pos, rng

        self._decode_fns[(kv_len, steps, with_history, False)] = \
            decode_call
        return decode_call

    def _build_fsm_decode(self, kv_len: int, steps: int,
                          with_history: bool, rows, max_len: int):
        """The constrained K-step decode programs (see _get_decode_fn).
        Carry gains the per-slot FSM state; the union tables ride as
        ordinary (non-donated) arguments, so arena growth re-uploads
        without recompiling, and the executables key only on the
        bucketed table shapes.

        DELIBERATE duplication of _get_decode_fn's scatter step bodies
        (KEEP THEM IN SYNC — any change to count/forward/penalty/
        sample there must land here too): the unconstrained variants'
        byte-identical-executable guarantee is an acceptance-tested
        contract, and sharing closures would put every future fsm-side
        edit one trace-time branch away from perturbing it."""
        sv = self.sample_vocab
        bsz = self.kv_block_size
        pallas_paged = self.paged and self.use_pallas_attention
        pallas_dense = (self.use_pallas_attention and not self.paged
                        and kv_len % 128 == 0)
        powers = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

        def masked(lg, fst, masks):
            bits = masks[fst]                        # [S, W] gather
            # Unpack by broadcast-test-reshape (cheaper than a [S, sv]
            # word gather: no per-element index math, and XLA fuses
            # the bit test straight into the select).
            allow = (bits[:, :, None]
                     & powers[None, None, :]) != 0   # [S, W, 32]
            allow = allow.reshape(bits.shape[0], -1)[:, :sv]
            return jnp.where(allow, lg, jnp.float32(-1e30))

        def advance(fst, nxt, act, sel, cls, nexts):
            ns = nexts[fst, cls[sel, nxt]]
            return jnp.where(act, ns, fst)

        if with_history:
            @partial(jax.jit, donate_argnums=(1, 2, 3, 4))
            def decode_fsm_hist(params, cache: KVCache, history, counts,
                                fsm_state, cur_tokens, positions,
                                active, temps, topks, topps, reps,
                                press, freqs, rng, sel, masks, cls,
                                nexts, bt=None):
                def step(carry, _):
                    ck, cv, ks, vs, hist, cnt, fst, cur, pos, key = carry
                    key, sub = jax.random.split(key)
                    act = jnp.logical_and(active, pos < kv_len)
                    wp = jnp.where(act, pos, max_len)
                    hist = hist.at[rows, wp].set(cur, mode="drop",
                                                 unique_indices=True)
                    cnt = cnt.at[rows, cur].add(act.astype(jnp.int32),
                                                unique_indices=True)
                    logits, newc = forward_decode(
                        params, self.cfg, cur, pos,
                        KVCache(ck, cv, ks, vs), act,
                        attn_len=kv_len,
                        pallas_int8=self.use_pallas_int8,
                        pallas_int4=self.use_pallas_int4,
                        block_table=bt, block_size=bsz,
                        pallas_paged=pallas_paged,
                        pallas_dense=pallas_dense)
                    lg = apply_penalties(logits[:, :sv], cnt, reps,
                                         press, freqs)
                    lg = masked(lg, fst, masks)
                    nxt = sample_tokens(lg, sub, temps, topks, topps,
                                        method=self.sampling_method)
                    fst = advance(fst, nxt, act, sel, cls, nexts)
                    pos = pos + act.astype(pos.dtype)
                    return (newc.k, newc.v, newc.k_scale, newc.v_scale,
                            hist, cnt, fst, nxt, pos, key), nxt

                (ck, cv, ks, vs, hist, cnt, fst, cur, pos, rng), toks \
                    = jax.lax.scan(
                        step, (cache.k, cache.v, cache.k_scale,
                               cache.v_scale, history, counts,
                               fsm_state, cur_tokens, positions, rng),
                        None, length=steps)
                return KVCache(ck, cv, ks, vs), hist, cnt, fst, toks, \
                    cur, pos, rng

            return decode_fsm_hist

        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def decode_fsm(params, cache: KVCache, counts, fsm_state,
                       cur_tokens, positions, active, temps, topks,
                       topps, reps, press, freqs, rng, sel, masks, cls,
                       nexts, bt=None):
            def step(carry, _):
                ck, cv, ks, vs, cnt, fst, cur, pos, key = carry
                key, sub = jax.random.split(key)
                act = jnp.logical_and(active, pos < kv_len)
                cnt = cnt.at[rows, cur].add(act.astype(jnp.int32),
                                            unique_indices=True)
                logits, newc = forward_decode(
                    params, self.cfg, cur, pos,
                    KVCache(ck, cv, ks, vs), act,
                    attn_len=kv_len,
                    pallas_int8=self.use_pallas_int8,
                    pallas_int4=self.use_pallas_int4,
                    block_table=bt, block_size=bsz,
                    pallas_paged=pallas_paged,
                    pallas_dense=pallas_dense)
                lg = apply_penalties(logits[:, :sv], cnt, reps,
                                     press, freqs)
                lg = masked(lg, fst, masks)
                nxt = sample_tokens(lg, sub, temps, topks, topps,
                                    method=self.sampling_method)
                fst = advance(fst, nxt, act, sel, cls, nexts)
                pos = pos + act.astype(pos.dtype)
                return (newc.k, newc.v, newc.k_scale, newc.v_scale,
                        cnt, fst, nxt, pos, key), nxt

            (ck, cv, ks, vs, cnt, fst, cur, pos, rng), toks = \
                jax.lax.scan(
                    step, (cache.k, cache.v, cache.k_scale,
                           cache.v_scale, counts, fsm_state,
                           cur_tokens, positions, rng), None,
                    length=steps)
            return KVCache(ck, cv, ks, vs), cnt, fst, toks, cur, pos, \
                rng

        return decode_fsm

    def _get_spec_decode_fn(self, kv_len: int, steps: int):
        """K speculative steps in one jitted call (single-device scatter
        path). Each step, entirely on device:

        1. maintain the history invariant ``history[s, pos] = cur``;
        2. DRAFT via prompt-lookup: find the most recent prior
           occurrence of the current token in the slot's history and
           propose the G tokens that followed it;
        3. VERIFY current + draft (T = G+1 positions) in one
           ``forward_decode_multi`` block — same weight-streaming cost
           as ~one plain step at small batch, since decode is
           weight-bound;
        4. ACCEPT: sample every position; keep the longest prefix where
           the sample equals the draft; emit accepted+1 tokens (the
           first mismatch IS the residual-distribution sample, so the
           output distribution is exactly the plain-decode one);
        5. append the emitted tokens to the history, advance positions
           by n_out.

        Rejected positions' KV is garbage but unreachable: attention
        masks to each query's absolute position, and the next block's
        writes start at the accepted length, overwriting it first.
        Returns per-step (tokens [K, S, T], n_out [K, S]); the host
        consumes the first n_out tokens per row.
        """
        key = (kv_len, steps)
        fn = self._spec_fns.get(key)
        if fn is not None:
            return fn
        self._note_compile("spec_decode", kv_len=kv_len, steps=steps)
        from fasttalk_tpu.models.llama import forward_decode_multi

        G = self.spec_draft
        T = G + 1
        S = self.num_slots
        max_len = self.max_len
        sv = self.sample_vocab

        bsz = self.kv_block_size
        # The verify block (T = G+1 positions) runs through the
        # multi-token q generalisation of the Pallas kernels — the
        # same gates as the plain decode families (_get_decode_fn).
        pallas_paged = self.paged and self.use_pallas_attention
        pallas_dense = (self.use_pallas_attention and not self.paged
                        and kv_len % 128 == 0)

        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def spec_call(params, cache: KVCache, history, counts, cur_tokens,
                      positions, active, temps, topks, topps,
                      reps, press, freqs, rng, bt=None):
            rows = jnp.arange(S)

            def step(carry, _):
                ck, cv, hist, cnt, cur, pos, key = carry
                # Need T columns of cache headroom inside this bucket;
                # slots without it sit the step out (the dispatcher
                # falls back to plain decode before this can starve a
                # request — see _dispatch_decode).
                act = jnp.logical_and(active, pos + T <= kv_len)
                wp = jnp.where(act, pos, max_len)
                hist = hist.at[rows, wp].set(cur, mode="drop",
                                             unique_indices=True)
                # Penalty base counts: the fed token (emitted last
                # block) counts now, same as the plain decode step.
                cnt = cnt.at[rows, cur].add(act.astype(jnp.int32),
                                            unique_indices=True)
                idx = jnp.arange(max_len)
                m = jnp.logical_and(hist == cur[:, None],
                                    idx[None, :] < pos[:, None])
                j = jnp.max(jnp.where(m, idx[None, :], -1), axis=1)
                start = jnp.clip(j + 1, 0, max_len - 1)
                didx = jnp.clip(start[:, None] + jnp.arange(G)[None, :],
                                0, max_len - 1)
                drafts = jnp.take_along_axis(hist, didx, axis=1)  # [S, G]
                tokens_in = jnp.concatenate([cur[:, None], drafts], 1)
                logits, newc = forward_decode_multi(
                    params, self.cfg, tokens_in, pos, KVCache(ck, cv),
                    act, attn_len=kv_len,
                    pallas_int8=self.use_pallas_int8,
                    pallas_int4=self.use_pallas_int4,
                    block_table=bt, block_size=bsz,
                    pallas_paged=pallas_paged,
                    pallas_dense=pallas_dense)
                key, sub = jax.random.split(key)
                # EXACT per-position penalty counts, without vocab-wide
                # per-position intermediates: block position j is
                # conditioned on fed tokens cur, d_1..d_j — if position
                # j's sample is ever emitted, those drafts were accepted
                # (= emitted), so plain decode would have counted them.
                # Only the <= G draft-token columns can differ from the
                # base counts, so penalise everything against the base
                # [S, 1, V] (broadcast, fused by XLA), then re-penalise
                # just those entries with their within-block counts and
                # scatter them in. Keeps speculative decoding exactly
                # distribution-preserving under penalties.
                lgf = logits[..., :sv].astype(jnp.float32)  # [S, T, sv]
                r3 = reps[:, None, None]
                p3 = press[:, None, None]
                f3 = freqs[:, None, None]
                lg = penalize_values(
                    lgf, cnt[:, None, :].astype(jnp.float32), r3, p3, f3)
                # occ[s, i, k]: occurrences of d_i among d_1..d_{k+1};
                # extra count of token d_i at block position j is its
                # occurrence count among the fed d_1..d_j.
                eq = (drafts[:, :, None] == drafts[:, None, :]) \
                    .astype(jnp.float32)                      # [S, G, G]
                extra = jnp.concatenate(
                    [jnp.zeros((S, G, 1), jnp.float32),
                     jnp.cumsum(eq, axis=2)], axis=2)         # [S, G, T]
                dcl = jnp.minimum(drafts, sv - 1)
                dcol = jnp.broadcast_to(dcl[:, None, :], (S, T, G))
                raw = jnp.take_along_axis(lgf, dcol, axis=2)  # [S, T, G]
                base_c = jnp.take_along_axis(cnt, dcl, axis=1) \
                    .astype(jnp.float32)                      # [S, G]
                c_true = base_c[:, None, :] \
                    + jnp.swapaxes(extra, 1, 2)               # [S, T, G]
                corr = penalize_values(raw, c_true, r3, p3, f3)
                # Equal drafts get equal corrected values, so the
                # duplicate-index scatter is value-consistent;
                # out-of-vocab draft ids (prompt tokens beyond the
                # tokenizer vocab) drop — they can never be sampled.
                scat = jnp.where(
                    jnp.broadcast_to((drafts < sv)[:, None, :],
                                     (S, T, G)), dcol, sv)
                lg = lg.at[jnp.arange(S)[:, None, None],
                           jnp.arange(T)[None, :, None],
                           scat].set(corr, mode="drop")
                t_samp = sample_tokens(
                    lg.reshape(S * T, sv), sub, jnp.repeat(temps, T),
                    jnp.repeat(topks, T), jnp.repeat(topps, T),
                    method=self.sampling_method).reshape(S, T)
                match = (t_samp[:, :-1] == drafts).astype(jnp.int32)
                a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # 0..G
                n_out = jnp.where(act, a + 1, 0)
                new_cur = jnp.where(
                    act, jnp.take_along_axis(t_samp, a[:, None], 1)[:, 0],
                    cur)
                out_idx = pos[:, None] + 1 + jnp.arange(T)[None, :]
                keep = jnp.arange(T)[None, :] < n_out[:, None]
                hist = hist.at[
                    rows[:, None], jnp.where(keep, out_idx, max_len)].set(
                    t_samp, mode="drop")
                # Commit accepted drafts to the counts (they were fed
                # AND emitted). The residual sample t_samp[:, a] is
                # new_cur — counted when fed next block, like plain
                # decode's sampled token.
                add = jnp.arange(T)[None, :] < (n_out - 1)[:, None]
                cnt = cnt.at[rows[:, None],
                             jnp.where(add, t_samp, sv)].add(
                    jnp.int32(1), mode="drop")
                pos = pos + n_out
                # n_out packed as a trailing column: ONE host fetch per
                # call (a tuple fetch costs two serial link round trips
                # on relayed attach paths).
                packed = jnp.concatenate([t_samp, n_out[:, None]], axis=1)
                return (newc.k, newc.v, hist, cnt, new_cur, pos, key), \
                    packed

            (ck, cv, hist, cnt, cur, pos, rng), toks = jax.lax.scan(
                step, (cache.k, cache.v, history, counts, cur_tokens,
                       positions, rng), None, length=steps)
            return (KVCache(ck, cv), hist, cnt, toks, cur, pos, rng)

        self._spec_fns[key] = spec_call
        return spec_call

    # Dense-stamp alignment: shares round down to this granule (the
    # same minimum the slot scan uses), not to a power of two — the r4
    # pow2 bucketing (_share_granule) wasted up to HALF of a matched
    # prefix on the stamp path. The executable family stays bounded at
    # one per pow2 chunk length because _stamp_prefix decomposes the
    # share into descending pow2 chunks over an offset-parameterized
    # copy (the offset is a traced operand, not part of the jit key).
    _STAMP_GRANULE = 16

    @classmethod
    def _stamp_chunks(cls, share: int) -> list[tuple[int, int]]:
        """(offset, length) power-of-two chunks exactly covering
        ``share`` rounded down to the stamp granule. At most
        log2(max_len) chunks, each >= the granule."""
        share -= share % cls._STAMP_GRANULE
        out: list[tuple[int, int]] = []
        off = 0
        while off < share:
            rem = share - off
            chunk = 1 << (rem.bit_length() - 1)
            out.append((off, chunk))
            off += chunk
        return out

    def _stamp_prefix(self, src: int, dst: int, share: int) -> int:
        """Dense shared-prefix stamp: copy the source slot's leading
        rows onto ``dst`` in pow2 chunks (granule-aligned, so at most
        granule-1 matched tokens are wasted instead of up to half
        under the old pow2 round-down). Returns rows stamped."""
        done = 0
        for off, ln in self._stamp_chunks(share):
            self._sink("prefix_copy", share=ln, off=off, src=src,
                       dst=dst)
            self.cache = self._get_prefix_copy_fn(ln)(
                self.cache, np.int32(src), np.int32(dst),
                np.int32(off))
            done = off + ln
        return done

    def _get_prefix_copy_fn(self, plen: int):
        """Copy one slot's KV rows [off, off+plen) onto another slot —
        one chunk of the shared-prefix stamp. Pure HBM traffic
        (2·L·plen·Kv·H elements), ordered against prefills and decode
        calls by the donated-cache chain like every other cache op.
        The row offset is a traced operand: one executable serves
        every chunk position, keeping the family at one entry per
        pow2 chunk length."""
        key = ("pcopy", plen)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        shape = (self.cfg.num_layers, 1, plen, self.cfg.num_kv_heads,
                 self.cfg.head_dim)
        # Quantized tier: the stamp copies int8 rows + their scale rows
        # — half the HBM traffic of the bf16 stamp, same ordering
        # guarantees (donated-cache chain).
        kvq = self.kv_quant
        sshape = (self.cfg.num_layers, 1, plen, self.kv_scale_granule)

        @partial(jax.jit, donate_argnums=(0,))
        def prefix_copy(cache: KVCache, src, dst, off):
            rk = jax.lax.dynamic_slice(cache.k, (0, src, off, 0, 0),
                                       shape)
            rv = jax.lax.dynamic_slice(cache.v, (0, src, off, 0, 0),
                                       shape)
            new_k = jax.lax.dynamic_update_slice(cache.k, rk,
                                                 (0, dst, off, 0, 0))
            new_v = jax.lax.dynamic_update_slice(cache.v, rv,
                                                 (0, dst, off, 0, 0))
            if not kvq:
                return KVCache(new_k, new_v)
            rks = jax.lax.dynamic_slice(cache.k_scale,
                                        (0, src, off, 0), sshape)
            rvs = jax.lax.dynamic_slice(cache.v_scale,
                                        (0, src, off, 0), sshape)
            return KVCache(
                new_k, new_v,
                jax.lax.dynamic_update_slice(cache.k_scale, rks,
                                             (0, dst, off, 0)),
                jax.lax.dynamic_update_slice(cache.v_scale, rvs,
                                             (0, dst, off, 0)))

        self._prefill_fns[key] = prefix_copy
        return prefix_copy

    # ---------------- session KV host-offload tier ----------------
    # (kvcache/: hostpool + offload + policy; docs/KVCACHE.md)

    def _get_kv_slice_fn(self, bucket: int):
        """Read one slot's leading ``bucket`` KV rows (no donation —
        the cache chain is untouched; see kvcache/offload.py)."""
        key = ("kvslice", bucket)
        fn = self._prefill_fns.get(key)
        if fn is None:
            self._note_compile("kv_offload", bucket=bucket,
                               **self._kvq_attrs)
            fn = make_kv_slice_fn(self.cfg, bucket,
                                  self.kv_scale_granule)
            self._prefill_fns[key] = fn
        return fn

    def _get_kv_restore_fn(self, bucket: int):
        """Write parked rows back into a slot (donated cache — chains
        with prefill/decode like every other cache op)."""
        key = ("kvrestore", bucket)
        fn = self._prefill_fns.get(key)
        if fn is None:
            self._note_compile("kv_restore", bucket=bucket,
                               **self._kvq_attrs)
            fn = make_kv_restore_fn(self.cfg, bucket, KVCache,
                                    self.kv_scale_granule)
            self._prefill_fns[key] = fn
        return fn

    def _get_paged_kv_slice_fn(self, bucket: int):
        key = ("pkvslice", bucket)
        fn = self._prefill_fns.get(key)
        if fn is None:
            self._note_compile("kv_offload", bucket=bucket,
                               kv_layout="paged", **self._kvq_attrs)
            fn = make_paged_kv_slice_fn(self.cfg, bucket,
                                        self.kv_scale_granule)
            self._prefill_fns[key] = fn
        return fn

    def _get_paged_kv_restore_fn(self, bucket: int):
        key = ("pkvrestore", bucket)
        fn = self._prefill_fns.get(key)
        if fn is None:
            self._note_compile("kv_restore", bucket=bucket,
                               kv_layout="paged", **self._kvq_attrs)
            fn = make_paged_kv_restore_fn(self.cfg, bucket, KVCache,
                                          self.kv_scale_granule)
            self._prefill_fns[key] = fn
        return fn

    def _park_on_evict(self, victim: Slot) -> None:
        """SlotManager eviction hook (engine thread, inside acquire):
        snapshot the victim's kept KV rows to the host pool before the
        slot is cleared for its new session. The slice program is
        dispatched here (ordered before the new occupant's prefill by
        dispatch order); the blocking device→host fetch runs on the
        offload thread, so admission never waits on the copy."""
        if not self._kv_pool.enabled:
            return
        kept = min(victim.kv_written, len(victim.tokens))
        if kept < self._kv_policy.min_tokens:
            return
        if self._kv_pool.parked_len(victim.session_id) >= kept \
                or self._kv_offload.parking(victim.session_id):
            return  # an up-to-date snapshot is parked or in flight
        self._park_slot(victim, kept)

    def _park_slot(self, slot: Slot, kept: int) -> None:
        bucket = kv_bucket(kept, self.max_len)
        t0 = time.monotonic()
        trim = None
        if self.paged:
            # Paged tier: gather the slot's BLOCK LIST (flat pool rows
            # via its table) rather than a dense slot slice, and trim
            # the host entry to exact per-block bytes — the pool
            # budget accounts blocks, not power-of-two padding.
            bucket = max(bucket, self.kv_block_size)
            trim = (blocks_for(kept, self.kv_block_size)
                    * self.kv_block_size)
            out = self._get_paged_kv_slice_fn(bucket)(
                self.cache,
                self._arg(self._paged_read_indices(slot.index,
                                                   bucket)))
        else:
            out = self._get_kv_slice_fn(bucket)(
                self.cache, np.int32(slot.index))
        if self._tracer.enabled:
            # Device-time attribution row for the park slice (no token
            # stats — engine_op records feed only the busy union and
            # the per-program ledger).
            self._tracer.step(
                "engine_op", t0, time.monotonic(), kind="kv_offload",
                program=program_key(
                    "kv_offload", bucket=bucket, **self._kvq_attrs,
                    **({"kv_layout": "paged"} if self.paged else {})))
        # Quantized tier: the slice carries int8 rows + scale rows;
        # the pool entry's nbytes (and therefore the budget, the
        # kv_host_bytes gauge and the copy-bandwidth EMA) see the
        # honest quantized footprint.
        scales = (out[2], out[3]) if self.kv_quant else None
        self._kv_offload.park(slot.session_id, list(slot.tokens[:kept]),
                              kept, bucket, out[0], out[1], t0,
                              scales=scales, trim_rows=trim)

    def _prefill_park_finish(self, req: _Request, slot: Slot) -> None:
        """Terminal step of a ``prefill_only`` request (disaggregated
        prefill tier, router/disagg.py): snapshot the freshly written
        prompt KV to the host pool and finish with reason
        ``prefill_parked`` — no first-token sample, no activation, the
        slot frees immediately. The park's D2H fetch runs on the
        offload copy thread; the router polls ``parked_kv_info`` until
        the entry lands before migrating it out. Engine thread only."""
        kept = min(slot.kv_written, len(slot.tokens))
        if kept >= 1 and self._kv_pool.enabled \
                and self._kv_pool.parked_len(req.session_id) < kept \
                and not self._kv_offload.parking(req.session_id):
            self._park_slot(slot, kept)
        self._finish(req, "prefill_parked")

    def _try_restore(self, req: _Request, slot: Slot,
                     prompt: list[int]) -> int:
        """Restore a returning session's kept prefix from the host pool
        into its freshly acquired slot. Returns the number of leading
        prompt tokens now resident (0 = no entry / policy chose
        prefill; the caller falls through to shared-prefix/full
        prefill). Engine thread only."""
        if not self._kv_pool.enabled:
            return 0
        entry = self._kv_pool.get(req.session_id)
        if entry is None:
            self._kv_pool.note_lookup(False)
            return 0
        # Same trust rules as slot-resident reuse: at least one prompt
        # token must run through the model, and only the matched prefix
        # is believable KV.
        match = _lcp(entry.tokens, prompt,
                     min(entry.kept, len(prompt) - 1))
        if not self._kv_policy.should_restore(match, entry.nbytes):
            self._kv_pool.note_lookup(False)
            return 0  # entry stays parked for a later, longer match
        if self.kv_quant and entry.k_scale is None:
            # A bf16-era entry cannot restore into the quantized cache
            # (unreachable within one engine lifetime — the pool is
            # engine-owned — but never corrupt KV over an assumption).
            self._kv_pool.note_lookup(False)
            return 0
        if self.paged and not self._kv_blocks.ensure(slot.index, match):
            # No blocks for the restored prefix: leave the entry
            # parked; the full-prefill fallback faces the admission
            # check next.
            self._kv_pool.note_lookup(False)
            return 0
        t0 = time.monotonic()
        try:
            if _fp.enabled:
                _fp.fire("kv.restore.dispatch",
                         request_id=req.request_id,
                         session_id=req.session_id)
            paged = self.paged
            if paged:
                fn = self._get_paged_kv_restore_fn(entry.bucket)
                # Scatter target: the freshly allocated block list
                # (positions past it carry distinct OOR indices and
                # drop — a restore allocates exactly
                # ceil(match/block_size) blocks).
                tgt = (self._arg(self._paged_write_indices(
                    slot.index, 0, entry.bucket)),)
            else:
                fn = self._get_kv_restore_fn(entry.bucket)
                tgt = (np.int32(slot.index),)
            k_arg, v_arg = entry.k_dev, entry.v_dev
            prestaged = k_arg is not None and v_arg is not None
            if not prestaged:  # prestage didn't land
                if paged:  # stored rows are block-trimmed: pad back
                    k_arg = self._arg(pad_rows(entry.k, entry.bucket))
                    v_arg = self._arg(pad_rows(entry.v, entry.bucket))
                else:
                    k_arg, v_arg = self._arg(entry.k), self._arg(entry.v)
            if self.kv_quant:
                # Scales ride with their rows (prestaged before
                # k_dev/v_dev on the copy thread, so prestaged rows
                # imply staged scales).
                ks_arg, vs_arg = entry.k_scale_dev, entry.v_scale_dev
                if not prestaged or ks_arg is None or vs_arg is None:
                    ks_arg = self._arg(pad_rows(entry.k_scale,
                                                entry.bucket)
                                       if paged else entry.k_scale)
                    vs_arg = self._arg(pad_rows(entry.v_scale,
                                                entry.bucket)
                                       if paged else entry.v_scale)
                self.cache = fn(self.cache, k_arg, v_arg, ks_arg,
                                vs_arg, *tgt)
            else:
                self.cache = fn(self.cache, k_arg, v_arg, *tgt)
        except Exception as e:
            # A failed restore dispatch must degrade to a full
            # prefill, never crash the engine thread mid-admission —
            # UNLESS the restore program already CONSUMED the donated
            # cache: serving on would use-after-free the dead buffer
            # at the next dispatch, a delayed and misattributed
            # crash. Re-raise into the engine crash path instead
            # (_abort_all + supervised restart rebuild the cache).
            if self.cache is None or getattr(
                    self.cache.k, "is_deleted", lambda: False)():
                log.critical(f"kv restore for {req.session_id} "
                             "consumed the donated cache before "
                             f"failing ({e}); escalating to restart")
                raise
            # The entry is purged — after a failed H2D its host copy
            # is suspect, and the byte accounting must stay exact
            # (purge removes exactly entry.nbytes).
            log.error(f"kv restore failed for {req.session_id}: {e}; "
                      "falling back to full prefill")
            if self.paged:
                # Release the blocks ensure() allocated for the failed
                # scatter: the slot's table must be EMPTY again, or
                # the shared-prefix alias stamp (which requires a
                # fresh table) corrupts refcounts on this admission.
                self._kv_blocks.truncate(slot.index, slot.kv_written)
            self._kv_pool.purge(req.session_id)
            self._kv_pool.note_lookup(False)
            return 0
        dt = time.monotonic() - t0
        slot.tokens = list(entry.tokens[:match])
        slot.kv_written = match
        if entry.imported:
            # Migrated-in prefix (disagg handoff / fleet migration):
            # donate the restored blocks to the radix tree NOW, while
            # this slot's table pins them — the decode tier's prefix
            # cache learns handed-off prefills at first use instead of
            # waiting for this stream to finish. Holds are exact: the
            # tree takes allocator holds through the same insert path
            # as every other donation.
            self._radix_insert_slot(slot)
        # Consumed: the KV is device-resident again; a later eviction
        # re-parks the (longer) history.
        self._kv_pool.take(req.session_id)
        self._kv_pool.note_lookup(True)
        self._kv_offload.note_restore(dt)
        if self._tracer.enabled:
            self._tracer.add_span(req.request_id, "kv_restore", t0,
                                  time.monotonic(), tokens=match,
                                  bytes=entry.nbytes,
                                  prestaged=prestaged)
            self._tracer.step(
                "engine_op", t0, time.monotonic(), kind="kv_restore",
                program=program_key(
                    "kv_restore", bucket=entry.bucket,
                    **self._kvq_attrs,
                    **({"kv_layout": "paged"} if paged else {})))
        return match

    def _kv_wait_discount(self, session_id: str,
                          prompt: list[int]) -> float:
        """Expected seconds a host-KV restore shaves off this request's
        service time (0 without a matching parked entry) — consulted by
        the scheduler's estimated-wait shed decision at submit time
        (asyncio side; entry token lists are immutable, so the LCP runs
        safely outside the pool lock)."""
        if not self._kv_pool.enabled:
            return 0.0
        entry = self._kv_pool.get(session_id)
        if entry is None:
            return 0.0
        match = _lcp(entry.tokens, prompt,
                     min(entry.kept, len(prompt) - 1))
        return self._kv_policy.restore_saving_s(match, entry.nbytes)

    def _kv_tick(self) -> None:
        """Once-a-second housekeeping on the engine loop: TTL-sweep the
        pool and park sessions idle past KV_PARK_IDLE_S. Idle parks
        keep the slot pinned — the resident KV still serves the fast
        path; the host copy is insurance, making a later eviction free
        and the history restorable across engine.restart()."""
        if not self._kv_pool.enabled:
            return
        now = time.monotonic()
        if now - self._kv_last_tick < 1.0:
            return
        self._kv_last_tick = now
        self._kv_pool.sweep(now)
        if self._kv_park_idle_s <= 0:
            return
        for slot in self.slots.slots:
            if slot.session_id is None or slot.active:
                continue
            kept = min(slot.kv_written, len(slot.tokens))
            if kept < self._kv_policy.min_tokens \
                    or now - slot.last_used < self._kv_park_idle_s:
                continue
            if self._kv_pool.parked_len(slot.session_id) >= kept \
                    or self._kv_offload.parking(slot.session_id):
                continue  # snapshot current or in flight
            self._park_slot(slot, kept)

    # ---- fleet fabric: cross-replica KV migration (docs/ROUTER.md).
    # All four run off the engine thread (router migrate worker /
    # serving handlers) and touch ONLY the thread-safe host pool — so
    # they keep working on a replica whose engine thread has died,
    # which is exactly when failover migration needs them.

    def export_parked_kv(self, session_id: str):
        entry = self._kv_pool.get(session_id)
        return None if entry is None else strip_device(entry)

    def parked_kv_info(self, session_id: str) -> tuple[int, int] | None:
        entry = self._kv_pool.get(session_id)
        return None if entry is None else (entry.kept, entry.nbytes)

    def drop_parked_kv(self, session_id: str) -> bool:
        return self._kv_pool.purge(session_id)

    def import_parked_kv(self, entry) -> bool:
        """Adopt a migrated entry: validate it against THIS engine's
        cache geometry (a mixed-tier fleet must refuse, never restore
        garbage), normalise the stored rows to this engine's layout
        (paged targets trim to exact block bytes, dense targets pad
        back to the power-of-two bucket), then insert. The put is
        atomic — a refusal at any step leaves the pool untouched."""
        from dataclasses import replace

        if not self._kv_pool.enabled:
            return False
        problem = entry_problem(entry)
        if problem is not None:
            log.warning(f"refused migrated KV for {entry.session_id}: "
                        f"{problem}")
            return False
        L, _, Kv, H = entry.k.shape
        if (L, Kv, H) != (self.cfg.num_layers, self.cfg.num_kv_heads,
                          self.cfg.head_dim):
            log.warning(
                f"refused migrated KV for {entry.session_id}: geometry "
                f"[{L},{Kv},{H}] != engine "
                f"[{self.cfg.num_layers},{self.cfg.num_kv_heads},"
                f"{self.cfg.head_dim}]")
            return False
        if entry.kept > self.max_len:
            log.warning(f"refused migrated KV for {entry.session_id}: "
                        f"kept {entry.kept} exceeds max_len "
                        f"{self.max_len}")
            return False
        if self.kv_quant:
            if entry.k_scale is None or entry.k.dtype != np.int8 \
                    or entry.k_scale.shape[2] != self.kv_scale_granule:
                log.warning(f"refused migrated KV for "
                            f"{entry.session_id}: not int8 rows with "
                            f"granule {self.kv_scale_granule} scales")
                return False
        elif entry.k_scale is not None or entry.k.dtype == np.int8:
            log.warning(f"refused migrated KV for {entry.session_id}: "
                        "quantized entry into a bf16-tier cache")
            return False
        elif entry.k.dtype != jnp.dtype(self.dtype):
            # dtype is part of the tier: a float32 entry in a bf16
            # cache passes every shape check but fails inside the
            # jitted restore program — refuse at import, not at
            # restore time.
            log.warning(f"refused migrated KV for {entry.session_id}: "
                        f"row dtype {entry.k.dtype} != engine cache "
                        f"dtype {jnp.dtype(self.dtype)}")
            return False
        bucket = kv_bucket(entry.kept, self.max_len)
        rows = bucket
        if self.paged:
            bucket = max(bucket, self.kv_block_size)
            rows = (blocks_for(entry.kept, self.kv_block_size)
                    * self.kv_block_size)

        def fit(arr):
            if arr is None:
                return None
            if arr.shape[1] > rows:
                return np.ascontiguousarray(arr[:, :rows])
            return pad_rows(arr, rows)

        k, v = fit(entry.k), fit(entry.v)
        ks, vs = fit(entry.k_scale), fit(entry.v_scale)
        nbytes = int(k.nbytes) + int(v.nbytes)
        if ks is not None:
            nbytes += int(ks.nbytes) + int(vs.nbytes)
        entry = replace(strip_device(entry), k=k, v=v, k_scale=ks,
                        v_scale=vs, bucket=bucket, nbytes=nbytes,
                        tokens=list(entry.tokens),
                        parked_at=time.monotonic(),
                        last_used=time.monotonic(), imported=True)
        # The session may have been released here before (tombstoned):
        # it is coming BACK via migration, so it may return — but the
        # tombstone falls only with a successful insert (a refused
        # import must keep guarding against stale in-flight parks).
        ok = self._kv_pool.put(entry, revive=True)
        if ok:
            # The imported session's next request is typically already
            # on the wire (disagg handoff: the decode stream admits
            # right behind the transfer) — stage the rows to the
            # device now so its restore dispatches H2D-free.
            self._kv_offload.prestage(entry.session_id)
        return ok

    # ---------------- paged KV tier ----------------
    # (KV_LAYOUT=paged — kvcache/blocks.py; docs/KVCACHE.md "Paged
    # tier". All methods engine-thread only unless noted.)

    def _on_slot_unpin(self, slot: Slot) -> None:
        """SlotManager unpin hook: a session leaving its slot (evict
        or release) drops its whole block table — aliased blocks
        survive through their other referents' refcounts. With the
        radix cache on, the departing session's clean prefix blocks
        are donated to the tree FIRST (holds taken before the table
        refs drop), so the next request inherits them instead of
        re-prefilling."""
        if self.paged:
            self._radix_insert_slot(slot)
            self._kv_blocks.release(slot.index)

    def _paged_table_np(self, nb: int) -> np.ndarray:
        """[S, nb] block-table argument for a decode call at KV bucket
        nb * block_size. Unallocated entries stay 0 — their rows sit
        beyond every slot's position mask."""
        tbl = np.zeros((self.num_slots, nb), np.int32)
        for s in range(self.num_slots):
            t = self._kv_blocks.table(s)
            n = min(len(t), nb)
            if n:
                tbl[s, :n] = t[:n]
        return tbl

    def _paged_read_indices(self, slot_index: int,
                            rows: int) -> np.ndarray:
        """Flat pool-row indices of one slot's logical positions
        0..rows (park slice / prefill gather region). Positions past
        the slot's table read pool row 0 — always masked or trimmed by
        the consumer."""
        bs = self.kv_block_size
        t = self._kv_blocks.table(slot_index)
        nb = -(-rows // bs)
        blocks = np.zeros((nb,), np.int64)
        n = min(len(t), nb)
        if n:
            blocks[:n] = t[:n]
        idx = (blocks[:, None] * bs
               + np.arange(bs)[None, :]).reshape(-1)[:rows]
        return idx.astype(np.int32)

    def _paged_write_indices(self, slot_index: int, start: int,
                             count: int) -> np.ndarray:
        """Flat pool-row indices for writing positions
        start..start+count (prefill chunk scatter). Every position must
        already have an allocated block (``ensure`` ran); positions
        past max_len get DISTINCT out-of-range indices and drop."""
        bs = self.kv_block_size
        t = self._kv_blocks.table(slot_index)
        pool_rows = self.kv_pool_blocks * bs
        out = np.empty((count,), np.int64)
        for i in range(count):
            pos = start + i
            blk = pos // bs
            if blk < len(t):
                out[i] = t[blk] * bs + pos % bs
            else:
                out[i] = pool_rows + slot_index * self.max_len + pos
        return out.astype(np.int32)

    def _paged_oob_indices(self, row: int, count: int) -> np.ndarray:
        """DISTINCT out-of-range flat indices for a padding row's
        scatter (mode="drop" + unique_indices needs them distinct even
        though they never land)."""
        base = (self.kv_pool_blocks * self.kv_block_size
                + (self.num_slots + row) * self.max_len)
        return (base + np.arange(count)).astype(np.int32)

    def _get_block_copy_fn(self):
        """Copy one block's rows (all layers, + scale rows on the
        quantized tier) between flat-pool offsets — the copy-on-write
        primitive behind partial-tail aliasing and divergence COW. One
        executable total, vs the dense tier's per-length prefix-copy
        family."""
        key = ("pblockcopy",)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        self._note_compile("kv_block_copy",
                           block_size=self.kv_block_size,
                           **self._kvq_attrs)
        bs = self.kv_block_size
        shape = (self.cfg.num_layers, bs, self.cfg.num_kv_heads,
                 self.cfg.head_dim)
        sshape = (self.cfg.num_layers, bs, self.kv_scale_granule)
        kvq = self.kv_quant

        @partial(jax.jit, donate_argnums=(0,))
        def block_copy(cache: KVCache, src_row, dst_row):
            rk = jax.lax.dynamic_slice(cache.k, (0, src_row, 0, 0),
                                       shape)
            rv = jax.lax.dynamic_slice(cache.v, (0, src_row, 0, 0),
                                       shape)
            new_k = jax.lax.dynamic_update_slice(cache.k, rk,
                                                 (0, dst_row, 0, 0))
            new_v = jax.lax.dynamic_update_slice(cache.v, rv,
                                                 (0, dst_row, 0, 0))
            if not kvq:
                return KVCache(new_k, new_v)
            rks = jax.lax.dynamic_slice(cache.k_scale,
                                        (0, src_row, 0), sshape)
            rvs = jax.lax.dynamic_slice(cache.v_scale,
                                        (0, src_row, 0), sshape)
            return KVCache(
                new_k, new_v,
                jax.lax.dynamic_update_slice(cache.k_scale, rks,
                                             (0, dst_row, 0)),
                jax.lax.dynamic_update_slice(cache.v_scale, rvs,
                                             (0, dst_row, 0)))

        self._prefill_fns[key] = block_copy
        return block_copy

    def _paged_copy_block(self, src_blk: int, dst_blk: int) -> None:
        bs = self.kv_block_size
        t0 = time.monotonic()
        self.cache = self._get_block_copy_fn()(
            self.cache, np.int32(src_blk * bs), np.int32(dst_blk * bs))
        if self._tracer.enabled:
            self._tracer.step(
                "engine_op", t0, time.monotonic(),
                kind="kv_block_copy",
                program=program_key("kv_block_copy", block_size=bs,
                                    **self._kvq_attrs))

    def _paged_sync_resident(self, slot: Slot) -> None:
        """Reconcile the slot's block table with its (possibly just
        truncated) trusted history: drop blocks past kv_written, and
        copy-on-write the tail block when it is shared and the next
        write would land inside it — an aliased prefix must never be
        written through."""
        alloc = self._kv_blocks
        kvw = slot.kv_written
        alloc.truncate(slot.index, kvw)
        tail = kvw % self.kv_block_size
        if tail and alloc.tail_shared(slot.index):
            pair = alloc.cow_tail(slot.index)
            if pair is None:
                # Pool empty: fall back to the block boundary — the
                # dropped tail rows re-prefill (never corrupt a shared
                # block over an allocation failure).
                aligned = kvw - tail
                slot.tokens = slot.tokens[:aligned]
                slot.kv_written = aligned
                alloc.truncate(slot.index, aligned)
                return
            self._paged_copy_block(*pair)

    def _paged_alias(self, src: Slot | None, slot: Slot,
                     share: int) -> int:
        """The paged shared-prefix stamp: alias the source's full
        blocks into this fresh slot's table (refcount bump, ZERO row
        copies) and copy-on-write the partially shared tail block.
        Returns the prompt tokens now resident."""
        if src is None or share < 16:
            return 0
        bs = self.kv_block_size
        alloc = self._kv_blocks
        full, tail = divmod(share, bs)
        n = alloc.alias(src.index, slot.index, full) if full else 0
        reused = n * bs
        if n == full and tail:
            blk = alloc.append_block(slot.index)
            if blk is not None:
                src_blk = alloc.table(src.index)[full]
                self._paged_copy_block(src_blk, blk)
                alloc.cow_copies += 1
                reused += tail
        return reused

    # ---------------- radix prefix cache ----------------
    # (kvcache/radix.py; docs/KVCACHE.md "Automatic prefix cache".)

    def _radix_insert_slot(self, slot: Slot) -> int:
        """Donate a slot's clean (fully written) prefix blocks to the
        radix tree. The tree takes allocator holds on blocks it did
        not already cache, so they survive the slot's release. Engine
        thread only; no device work."""
        tree = self._kv_radix
        if tree is None or slot.session_id is None:
            return 0
        kept = min(slot.kv_written, len(slot.tokens))
        if kept < self.kv_block_size:
            return 0
        return tree.insert(slot.tokens,
                           self._kv_blocks.table(slot.index),
                           written=kept)

    def _radix_admit(self, req: _Request, slot: Slot,
                     prompt: list[int]) -> int:
        """Alias the longest radix-cached block chain into this fresh
        slot (zero device copies; the delta prefills from a block
        boundary, so no COW is needed at match time). On a tree miss,
        the legacy cross-slot scan SEEDS the tree — the explicit stamp
        path is now a thin shim over radix insert — and the match
        retries. Returns leading prompt tokens now resident (0 = no
        usable chain, or a longer parked host entry should restore
        instead)."""
        tree = self._kv_radix
        if tree is None:
            return 0
        bs = self.kv_block_size
        # At least one prompt token must run through the model (same
        # trust rule as every other reuse path).
        max_blocks = (len(prompt) - 1) // bs
        if max_blocks <= 0:
            return 0
        blocks, _digest = tree.match(prompt, max_blocks=max_blocks)
        matched = len(blocks) * bs
        if self.shared_prefix and matched < max_blocks * bs:
            # Tree shorter than another slot's resident prefix: donate
            # that slot's clean blocks, then match again.
            src, share = self.slots.best_shared_prefix(slot, prompt)
            if src is not None \
                    and min(share, src.kv_written) // bs * bs > matched:
                self._radix_insert_slot(src)
                blocks, _digest = tree.match(
                    prompt, max_blocks=max_blocks, count=False)
                matched = len(blocks) * bs
        if matched < bs:
            return 0
        if self._kv_pool.enabled:
            # Host-offload interplay: a LONGER parked entry for this
            # session wins — one H2D copy beats prefilling the extra
            # delta; _try_restore runs next in the caller.
            entry = self._kv_pool.get(req.session_id)
            if entry is not None:
                hm = _lcp(entry.tokens, prompt,
                          min(entry.kept, len(prompt) - 1))
                if hm > matched and self._kv_policy.should_restore(
                        hm, entry.nbytes):
                    return 0
        self._kv_blocks.alias_blocks(slot.index, blocks)
        slot.tokens = list(prompt[:matched])
        slot.kv_written = matched
        tree.note_hit(matched)
        self._m_shared.inc(matched)
        return matched

    def _paged_reserve_tokens(self, req: _Request) -> int:
        """Decode-growth reserve the admission check must see free
        (KV_RESERVE_POLICY): 'fixed' covers the next
        KV_RESERVE_TOKENS of growth, 'max_tokens' the request's whole
        budget, 'none' admits on prefill fit alone (maximum packing,
        relies on mid-decode shedding)."""
        if self.kv_reserve_policy == "none":
            return 0
        if self.kv_reserve_policy == "max_tokens":
            return req.params.max_tokens
        return min(self.kv_reserve_tokens, req.params.max_tokens)

    def _paged_admissible(self, slot: Slot, req: _Request,
                          reused: int, todo: int) -> bool:
        """A request is admissible iff its prefill blocks fit now and
        the reserve policy's decode-growth horizon is also free
        (ROADMAP item 1's admission-by-blocks-in-use). Rejections shed
        with retry_after — the same taxonomy as a queue shed, so
        clients back off instead of erroring."""
        bs = self.kv_block_size
        # The prefill pads its LAST chunk to a bucket: admission must
        # cover that padded write horizon — reused + the full chunks +
        # the final chunk's bucket — not todo plus a whole extra
        # bucket (which would demand up to 2x the blocks prefill ever
        # ensures and shed requests that fit).
        last = (todo % self.prefill_chunk
                or min(max(1, todo), self.prefill_chunk))
        pad = next((b for b in _PREFILL_BUCKETS if b >= last),
                   _PREFILL_BUCKETS[-1])
        need_tokens = min(self.max_len,
                          reused + max(0, todo - last) + pad
                          + self._paged_reserve_tokens(req))
        need = blocks_for(need_tokens, bs) \
            - self._kv_blocks.slot_blocks(slot.index)
        avail = self._kv_blocks.available()
        if self._kv_radix is not None:
            # Unreferenced radix-held blocks are reclaimable on demand
            # (the allocator's pressure callback evicts them inside
            # _take), so admission counts them as free.
            avail += self._kv_radix.evictable_blocks()
        if need <= avail:
            return True
        self._paged_exhausted_finish(
            req, f"KV block pool exhausted: prompt needs {need} more "
                 f"{bs}-token blocks ({self._kv_blocks.available()} "
                 f"free of {self.kv_pool_blocks})")
        return False

    def _paged_retry_after(self) -> float:
        """Back-off hint for a block-exhaustion shed: roughly one
        service time must elapse for a running generation to finish
        and free its blocks."""
        ema = self._sched.stats().get("service_time_ema_s") or 0.0
        return round(max(0.5, float(ema)), 2)

    def _paged_exhausted_finish(self, req: _Request,
                                error: str) -> None:
        self._events.emit("kv_pressure", severity="warning",
                          coalesce_s=10.0, coalesce_key="blocks",
                          reason="block_pool_exhausted",
                          free=self._kv_blocks.available(),
                          total=self.kv_pool_blocks)
        self._finish(req, "error", error=error,
                     code="kv_blocks_exhausted",
                     retry_after=self._paged_retry_after())

    def _paged_wait_penalty(self, prompt_len: int) -> float:
        """Block-pressure term for the scheduler's estimated-wait shed
        (asyncio side, racy-read tolerable — it's an estimate): when
        the pool cannot currently hold this prompt, at least one
        running generation must finish first, so the wait estimate
        grows by ~one service time."""
        if not self.paged:
            return 0.0
        need = blocks_for(prompt_len, self.kv_block_size)
        avail = self._kv_blocks.available()
        if self._kv_radix is not None:
            avail += self._kv_radix.evictable_blocks()
        if need <= avail:
            return 0.0
        return self._paged_retry_after()

    def _paged_prepare_decode(self, worst_adv: int) -> bool:
        """Pre-allocate every running slot's blocks out to its worst-
        case write horizon for the next decode call (device positions
        lead the host mirrors by the in-flight calls' advances). On
        pool exhaustion, sheds the youngest running request (frees its
        blocks via session release) and retries — the rehearsed
        degradation, never a crash. Returns False when nothing is left
        to run. MUST run before _patch_slot_state so a shed's
        deactivation reaches the very next call."""
        lead = sum(self._paged_leads) + worst_adv
        while self._running:
            victim: _Request | None = None
            for s, req in list(self._running.items()):
                horizon = min(self.max_len,
                              int(self._positions[s]) + lead)
                if not self._kv_blocks.ensure(s, horizon):
                    victim = max(self._running.values(),
                                 key=lambda r: r.admitted_at or 0.0)
                    break
            if victim is None:
                return True
            log.warning(
                f"KV block pool exhausted mid-decode; shedding "
                f"{victim.request_id}")
            slot = victim.slot
            self._paged_exhausted_finish(
                victim, "KV block pool exhausted mid-decode: request "
                        "shed to free blocks")
            if slot is not None and slot.session_id is not None:
                # The shed must actually free blocks: drop the
                # session's residency (its next turn re-prefills).
                self.slots.release_session(slot.session_id)
                self._kv_pool.purge(slot.session_id)
        return False

    def _kv_read_rows(self, snapshot, kv_len: int) -> int:
        """KV rows one decode step actually streamed, for the perf
        ledger's bandwidth figure. Dense: the fixed shapes read the
        whole bucket for every slot. Paged: only blocks backing live
        rows are read (the block walk prunes per slot), so the ledger
        counts blocks-read — this is what stops /perf bw_util
        over-reporting a mixed-length batch as S x bucket traffic."""
        if not self.paged:
            return self.num_slots * kv_len
        bs = self.kv_block_size
        return sum(
            min(kv_len, blocks_for(int(self._positions[s]), bs) * bs)
            for s, _ in snapshot)

    def _paged_decode_args(self, kv_len: int):
        """The block-table extra argument for a paged decode dispatch
        (empty tuple on the dense tier, so call sites stay shared)."""
        if not self.paged:
            return ()
        nb = kv_len // self.kv_block_size
        return (self._arg(self._paged_table_np(nb)),)

    # ---------------- structured decoding ----------------
    # (fasttalk_tpu/structured/; docs/STRUCTURED.md)

    def _get_st_compiler(self) -> FSMCompiler:
        """The (schema, tokenizer) FSM compiler+cache. Lazy and lock-
        guarded: first touched from the asyncio side (generate), and a
        plain-serving engine never builds the vocab byte table at
        all — the subsystem stays zero-cost until first use."""
        if self._st_compiler is None:
            with self._st_compiler_lock:
                if self._st_compiler is None:
                    self._st_compiler = FSMCompiler(
                        self.tokenizer,
                        cache_size=self._st_cfg["cache_size"],
                        max_states=self._st_cfg["max_states"],
                        json_depth=self._st_cfg["json_depth"],
                        sample_vocab=self.sample_vocab)
        return self._st_compiler

    def _st_register(self, req: _Request) -> None:
        """Pin a constrained request's FSM into the device union arena
        (engine thread, at admission). Growing the arena re-packs state
        offsets, so with constrained calls in flight the pipeline is
        drained first — the host FSM mirrors become authoritative and
        the refreshed per-slot states cannot rewind the device copy.
        Raises ArenaFull when running requests pin the whole budget."""
        if self._st_arena is None:
            self._st_arena = FSMArena(
                self.sample_vocab,
                tuple(sorted(t for t in self.tokenizer.eos_ids
                             if 0 <= t < self.sample_vocab)),
                self.num_slots,
                state_budget=self._st_cfg["state_budget"])
        arena = self._st_arena
        before = arena.state_cap
        req.fsm_entry = arena.register(req.fsm)
        if arena.dirty:
            if any(r.fsm is not None for _, r in
                   [p for call in self._inflight for p in call[3]]):
                while self._inflight:
                    self._retire_oldest()
            if any(r.fsm is not None for _, _, r in
                   [e for _, ents in self._pending_firsts
                    for e in ents]):
                self._drain_firsts(block=True)
            self._st_upload_tables()
            # Offsets may have moved: refresh every ACTIVE constrained
            # slot's device state from the (now-authoritative) host
            # mirrors.
            for s, r in self._running.items():
                if r.fsm is not None and r.fsm_entry is not None:
                    self._st_sel[s] = r.fsm_entry.sel
                    self._st_dirty.add(s)
            if arena.state_cap != before:
                # New table shapes: the constrained decode executables
                # key on them (one compile per capacity bucket).
                self._note_compile("structured_tables",
                                   states=arena.state_cap,
                                   classes=arena.class_cap)

    def _st_upload_tables(self) -> None:
        arena = self._st_arena
        self._st_masks_dev = self._put(arena.masks)
        self._st_nexts_dev = self._put(arena.nexts)
        self._st_cls_dev = self._put(arena.cls)
        arena.dirty = False

    def _st_release(self, req: _Request) -> None:
        """Terminal-path cleanup for a constrained request (inside
        _finish): unpin the FSM (tables stay cached for the next
        request of the same schema) and park the slot's device state
        back in FREE so a later unconstrained occupant is untouched."""
        self._st_jf_pending.discard(req.request_id)
        if req.fsm_entry is not None and self._st_arena is not None:
            self._st_arena.release(req.fsm)
            req.fsm_entry = None
        slot = req.slot
        if slot is not None:
            self._st_sel[slot.index] = 0
            self._st_dirty.add(slot.index)

    def _st_global_state(self, slot_index: int) -> int:
        req = self._running.get(slot_index)
        if req is None or req.fsm is None or req.fsm_entry is None:
            return 0  # FREE
        return self._st_arena.global_state(req.fsm_entry,
                                           req.fsm_state)

    def _get_st_patch_fn(self):
        """Scatter host-authoritative FSM states onto the chained
        device vector (finish→FREE resets, arena-repack refreshes)."""
        if self._st_patch_fn is None:
            @partial(jax.jit, donate_argnums=(1,))
            def st_patch(packed, fst):
                dirty = packed[:, 0] > 0.5
                return jnp.where(dirty, packed[:, 1].astype(fst.dtype),
                                 fst)

            self._st_patch_fn = st_patch
        return self._st_patch_fn

    def _get_st_sample_fn(self):
        """Masked sample-and-place: complete a constrained prefill (or
        a jump-forward) by sampling the next token under the packed
        allowed-row of the request's current FSM state, scattering it
        into the decode chain's current-token vector AND advancing the
        slot's device FSM state — one program, no host round trip
        before the first decode call."""
        if self._st_sample_fn is None:
            self._note_compile("st_sample")
            sv = self.sample_vocab
            widx = jnp.arange(sv) // 32
            wsh = (jnp.arange(sv) % 32).astype(jnp.uint32)

            @partial(jax.jit, donate_argnums=(1, 2))
            def st_sample(last_logits, cur, fst, rng, cfg_row,
                          mask_row, cls, nexts):
                slot = cfg_row[0].astype(jnp.int32)
                state = cfg_row[4].astype(jnp.int32)
                sel = cfg_row[5].astype(jnp.int32)
                rng, sub = jax.random.split(rng)
                allow = ((mask_row[widx] >> wsh)
                         & jnp.uint32(1)).astype(bool)
                lg = jnp.where(allow,
                               last_logits[:sv].astype(jnp.float32),
                               jnp.float32(-1e30))
                tok = sample_tokens(
                    lg[None], sub, cfg_row[1][None],
                    cfg_row[2].astype(jnp.int32)[None],
                    cfg_row[3][None], method=self.sampling_method)
                ns = nexts[state, cls[sel, tok[0]]]
                return (tok, cur.at[slot].set(tok[0], mode="drop"),
                        fst.at[slot].set(ns, mode="drop"), rng)

            self._st_sample_fn = st_sample
        return self._st_sample_fn

    def _st_sample_place(self, req: _Request, slot: Slot,
                         last_logits: Any) -> None:
        """Run the masked sample-place for one constrained slot and
        queue the token's emission (same deferred-fetch discipline as
        plain prefill completion)."""
        entry = req.fsm_entry
        gstate = self._st_arena.global_state(entry, req.fsm_state)
        mask_row = pack_mask_row(req.fsm, req.fsm_state,
                                 self._st_arena.words,
                                 req.fsm.eos_ids)
        cfg_row = np.array([slot.index, req.params.temperature,
                            req.params.top_k, req.params.top_p,
                            gstate, entry.sel], np.float32)
        t0 = time.monotonic()
        first, self._cur_tokens, self._st_state_dev, self._rng_dev = \
            self._get_st_sample_fn()(
                last_logits, self._cur_tokens, self._st_state_dev,
                self._rng_dev, self._arg(cfg_row),
                self._arg(mask_row), self._st_cls_dev,
                self._st_nexts_dev)
        if self._tracer.enabled:
            self._tracer.step("engine_op", t0, time.monotonic(),
                              kind="st_sample",
                              program=program_key("st_sample"))
        # The program just wrote this slot's authoritative state
        # (post-first-token). A pending host-side patch for the slot —
        # the previous occupant's finish→FREE reset, queued before
        # this admission — is now obsolete and would REWIND the device
        # FSM by one token (the host mirror lags until the deferred
        # first-token fetch drains): drop it.
        self._st_dirty.discard(slot.index)
        self._defer_first(first, [(0, slot.index, req)])

    def _st_penalties_neutral(self, req: _Request) -> bool:
        p = req.params
        return (p.repeat_penalty == 1.0 and p.presence_penalty == 0.0
                and p.frequency_penalty == 0.0)

    def _st_note_jump_candidate(self, req: _Request) -> None:
        """Called per consumed token for constrained requests: when the
        new state opens a forced single-transition chain long enough to
        beat one pipeline bubble, queue a jump. Jump-forward needs
        neutral penalties (forced tokens bypass the on-device count
        maintenance); with penalties active the decode steps still emit
        the same forced tokens — only the speed-up is skipped."""
        if self._st_jf_min <= 0 or not self._st_penalties_neutral(req):
            return
        if req.fsm_state < 0 \
                or int(req.fsm.forced_tok[req.fsm_state]) < 0:
            return  # DONE/DEAD sentinel, or not a forced state
        chain, _ = req.fsm.forced_chain(req.fsm_state)
        if len(chain) >= self._st_jf_min:
            self._st_jf_pending.add(req.request_id)

    def _st_jump_forward(self) -> None:
        """SGLang-style compressed-FSM jump: when a constrained slot's
        FSM state has a single outgoing transition chain, emit the
        forced tokens directly — one prefill call writes their KV rows
        (model steps skipped entirely), the text streams immediately,
        and a masked sample from the chain-end state restarts ordinary
        decoding. Runs only with the pipeline empty, so the host FSM
        mirrors are authoritative and no in-flight call can double-emit
        the chain."""
        self._drain_firsts(block=True)
        pending, self._st_jf_pending = self._st_jf_pending, set()
        for rid in pending:
            req = self._by_id.get(rid)
            if req is None or req.finished or req.slot is None:
                continue
            slot = req.slot
            if self._running.get(slot.index) is not req:
                continue
            chain, _end = req.fsm.forced_chain(req.fsm_state)
            room = min(req.params.max_tokens - req.generated,
                       self.usable_len - len(slot.tokens) - 1,
                       self.prefill_chunk - 1)
            n = min(len(chain), room)
            if n < self._st_jf_min:
                continue
            chain = chain[:n]
            start = int(self._positions[slot.index])
            # Feed the not-yet-fed newest token plus the whole chain:
            # the returned last-token logits then predict the token
            # AFTER the chain — exactly what the masked sample needs.
            feed = [slot.tokens[-1]] + chain
            bucket = next((b for b in _PREFILL_BUCKETS
                           if b >= len(feed)), None)
            if bucket is None or start + bucket > self.max_len:
                continue  # no room: plain decode emits the chain
            if self.paged and not self._kv_blocks.ensure(
                    slot.index, start + bucket):
                continue  # no blocks: plain decode emits the chain
            t0 = time.monotonic()
            padded = np.zeros((bucket,), np.int32)
            padded[:len(feed)] = feed
            last_logits = self._run_chunk_prefill(
                slot, padded, start, n, bucket)
            self._positions[slot.index] = start + n + 1
            slot.kv_written = start + n + 1
            self._dirty_slots.add(slot.index)
            for tok in chain:
                if req.finished \
                        or self._running.get(slot.index) is not req:
                    break
                self._consume_token(req, tok)
                req.jump_tokens += 1
                self._m_st_jump.inc()
            self._flush_emit(req)
            if self._tracer.enabled:
                self._tracer.step(
                    "engine_prefill", t0, time.monotonic(),
                    bucket=bucket, tokens=len(feed), rows=bucket,
                    kind="jump_forward",
                    program=self._prefill_program(start, bucket),
                    flops=self._perf.call_flops(len(feed), start + n))
                self._tracer.add_span(
                    req.request_id, "jump_forward", t0,
                    time.monotonic(), tokens=n)
            if req.finished:
                continue
            self._st_sample_place(req, slot, last_logits)

    def _get_prefill_fn(self, chunk: int):
        fn = self._prefill_fns.get(chunk)
        if fn is not None:
            return fn
        self._note_compile("prefill", chunk=chunk, **self._kvq_attrs)
        kvq = self.kv_quant
        sslot_shape = (self.cfg.num_layers, 1, self.max_len,
                       self.kv_scale_granule)

        @partial(jax.jit, donate_argnums=(1,))
        def prefill_step(params, cache: KVCache, tokens, start, slot,
                         last_index):
            """Run one prompt chunk for one slot; returns last-token logits."""
            slot_shape = (self.cfg.num_layers, 1, self.max_len,
                          self.cfg.num_kv_heads, self.cfg.head_dim)
            lk = jax.lax.dynamic_slice(cache.k, (0, slot, 0, 0, 0), slot_shape)
            lv = jax.lax.dynamic_slice(cache.v, (0, slot, 0, 0, 0), slot_shape)
            if kvq:
                lks = jax.lax.dynamic_slice(cache.k_scale,
                                            (0, slot, 0, 0), sslot_shape)
                lvs = jax.lax.dynamic_slice(cache.v_scale,
                                            (0, slot, 0, 0), sslot_shape)
                small = KVCache(lk, lv, lks, lvs)
            else:
                small = KVCache(lk, lv)
            positions = start + jnp.arange(chunk)[None, :]
            logits, updated = forward(
                params, self.cfg, tokens[None, :], positions,
                small, start[None], blockwise=True,
                pallas_int8=self.use_pallas_int8,
                pallas_int4=self.use_pallas_int4,
                logits_indices=last_index[None])
            new_k = jax.lax.dynamic_update_slice(
                cache.k, updated.k, (0, slot, 0, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                cache.v, updated.v, (0, slot, 0, 0, 0))
            if kvq:
                return KVCache(
                    new_k, new_v,
                    jax.lax.dynamic_update_slice(
                        cache.k_scale, updated.k_scale, (0, slot, 0, 0)),
                    jax.lax.dynamic_update_slice(
                        cache.v_scale, updated.v_scale,
                        (0, slot, 0, 0))), logits[0, 0]
            return KVCache(new_k, new_v), logits[0, 0]

        self._prefill_fns[chunk] = prefill_step
        return prefill_step

    def _get_paged_prefill_fn(self, chunk: int, ctx: int):
        """Paged single-slot prompt chunk: gather the slot's logical
        0..ctx rows out of the flat pool (read_idx, host-built from
        the block table), run the UNCHANGED dense ``forward`` over the
        contiguous scratch region, then scatter only the chunk's
        written rows back through write_idx — gather-run-scatter is
        the same structure the dense batched path already uses for
        slot rows, so the model code needs no paged prefill variant.
        ``ctx`` is a KV bucket covering start+chunk."""
        key = ("pprefill", chunk, ctx)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        self._note_compile("prefill", chunk=chunk, ctx=ctx,
                           kv_layout="paged", **self._kvq_attrs)
        kvq = self.kv_quant

        @partial(jax.jit, donate_argnums=(1,))
        def paged_prefill_step(params, cache: KVCache, tokens, start,
                               read_idx, write_idx, last_index):
            gk = cache.k[:, read_idx][:, None]  # [L, 1, ctx, Kv, H]
            gv = cache.v[:, read_idx][:, None]
            if kvq:
                small = KVCache(gk, gv,
                                cache.k_scale[:, read_idx][:, None],
                                cache.v_scale[:, read_idx][:, None])
            else:
                small = KVCache(gk, gv)
            positions = start + jnp.arange(chunk)[None, :]
            logits, upd = forward(
                params, self.cfg, tokens[None, :], positions,
                small, start[None], blockwise=True,
                pallas_int8=self.use_pallas_int8,
                pallas_int4=self.use_pallas_int4,
                logits_indices=last_index[None])

            def written(arr):  # [L, 1, ctx, ...] -> the chunk's rows
                sizes = (arr.shape[0], 1, chunk) + arr.shape[3:]
                zeros = (0,) * (arr.ndim - 3)
                return jax.lax.dynamic_slice(
                    arr, (0, 0, start) + zeros, sizes)[:, 0]

            new_k = cache.k.at[:, write_idx].set(
                written(upd.k), mode="drop", unique_indices=True)
            new_v = cache.v.at[:, write_idx].set(
                written(upd.v), mode="drop", unique_indices=True)
            if kvq:
                return KVCache(
                    new_k, new_v,
                    cache.k_scale.at[:, write_idx].set(
                        written(upd.k_scale), mode="drop",
                        unique_indices=True),
                    cache.v_scale.at[:, write_idx].set(
                        written(upd.v_scale), mode="drop",
                        unique_indices=True)), logits[0, 0]
            return KVCache(new_k, new_v), logits[0, 0]

        self._prefill_fns[key] = paged_prefill_step
        return paged_prefill_step

    def _run_chunk_prefill(self, slot: Slot, padded: np.ndarray,
                           start: int, last_index: int, bucket: int):
        """Dispatch one single-slot prefill chunk on the layout's
        program (dense slot slice or paged gather/scatter) and return
        the last-token logits. Paged callers must have ensured blocks
        for start+bucket."""
        if self.paged:
            ctx = next((b for b in _KV_BUCKETS
                        if b >= start + bucket and b <= self.max_len),
                       self.max_len)
            fn = self._get_paged_prefill_fn(bucket, ctx)
            self.cache, last = fn(
                self.params, self.cache, self._arg(padded),
                np.int32(start),
                self._arg(self._paged_read_indices(slot.index, ctx)),
                self._arg(self._paged_write_indices(slot.index, start,
                                                    bucket)),
                np.int32(last_index))
            return last
        fn = self._get_prefill_fn(bucket)
        self.cache, last = fn(self.params, self.cache,
                              self._arg(padded), np.int32(start),
                              np.int32(slot.index),
                              np.int32(last_index))
        return last

    def _ring_prefill_eligible(self, start: int, n_tokens: int) -> int:
        """If this fresh prompt should prefill through ring attention,
        return its (power-of-two) bucket; else 0.

        Eligible when the engine runs on a mesh with sp > 1, the prompt
        starts a fresh slot (ring attention is pure self-attention —
        a non-zero start would need cache rows the ring never visits),
        and it is long enough that one chip's attention working set is
        the thing to avoid (>= max_len/sp — the per-chip KV shard; the
        module's O(T/sp) memory promise, parallel/ring_attention.py).
        """
        if self.mesh is None or start != 0:
            return 0
        sp = self.mesh.shape.get("sp", 1)
        if sp <= 1 or n_tokens < max(256, self.max_len // sp):
            return 0
        bucket = 1 << (n_tokens - 1).bit_length()  # next power of two
        bucket = max(bucket, 2 * sp)
        if bucket > self.max_len or bucket % sp:
            return 0
        return bucket

    def _get_ring_prefill_fn(self, bucket: int):
        """Whole-prompt prefill for ONE slot with attention routed
        through parallel.ring_attention (VERDICT r4 #4): Q/K/V stay
        sequence-sharded over "sp" and K/V blocks rotate the ICI ring,
        so per-chip attention memory is O(T/sp) — where the default
        GSPMD lowering all-gathers K/V per chip. K/V are also written
        into the slot's (sp-sharded) cache rows, so decode attends the
        exact rows the ring produced. Single call for the full
        (bucketed) prompt — chunked prefill cannot ride the ring, since
        a later chunk attends cache rows the rotation never visits."""
        key = ("ring", bucket)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        self._note_compile("ring_prefill", bucket=bucket)
        from fasttalk_tpu.parallel.train import ring_override

        ring = ring_override(self.mesh)

        @partial(jax.jit, donate_argnums=(1,))
        def ring_prefill(params, cache: KVCache, tokens, slot,
                         last_index):
            slot_shape = (self.cfg.num_layers, 1, self.max_len,
                          self.cfg.num_kv_heads, self.cfg.head_dim)
            lk = jax.lax.dynamic_slice(cache.k, (0, slot, 0, 0, 0),
                                       slot_shape)
            lv = jax.lax.dynamic_slice(cache.v, (0, slot, 0, 0, 0),
                                       slot_shape)
            positions = jnp.arange(bucket)[None, :]
            logits, updated = forward(
                params, self.cfg, tokens[None, :], positions,
                KVCache(lk, lv), jnp.zeros((1,), jnp.int32),
                attn_override=ring, override_write=True,
                logits_indices=last_index[None])
            new_k = jax.lax.dynamic_update_slice(
                cache.k, updated.k, (0, slot, 0, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                cache.v, updated.v, (0, slot, 0, 0, 0))
            return KVCache(new_k, new_v), logits[0, 0]

        self._prefill_fns[key] = ring_prefill
        return ring_prefill

    def _get_batched_prefill_fn(self, chunk: int, group: int, ctx: int):
        """One prompt chunk for ``group`` slots at once.

        Gathers the first ``ctx`` KV positions of the target slots (the
        forward never reads or writes past start+chunk <= ctx, and
        gathering full max_len rows would transiently double the KV
        cache's HBM), runs one [group, chunk] forward with per-row write
        offsets, scatters the region back. Padding rows carry
        write_mask=False and an out-of-range slot index, so their
        scatter is dropped.

        The per-row scalars travel in ONE packed f32 array (rowcfg
        [group, 7]: slot, start, last_idx, mask, temp, top_k, top_p —
        all exactly representable) and the sampled first tokens are
        scattered into the decode chain's current-token vector inside
        the same program: on relayed devices every extra transfer or
        eager op costs a fixed multi-ms turnaround, so the whole burst
        is one host→device call.
        """
        key = (chunk, group, ctx)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        self._note_compile("batched_prefill", chunk=chunk, group=group,
                           ctx=ctx, **self._kvq_attrs)
        replicate = self._replicate_sharding()
        kvq = self.kv_quant

        @partial(jax.jit, donate_argnums=(1,))
        def batched_prefill(params, cache: KVCache, tokens, rowcfg,
                            cur, rng):
            slot_idx = rowcfg[:, 0].astype(jnp.int32)
            starts = rowcfg[:, 1].astype(jnp.int32)
            last_idx = rowcfg[:, 2].astype(jnp.int32)
            mask = rowcfg[:, 3] > 0.5
            temps, topks, topps = (rowcfg[:, 4],
                                   rowcfg[:, 5].astype(jnp.int32),
                                   rowcfg[:, 6])
            gk = cache.k[:, slot_idx, :ctx]  # [L, group, ctx, Kv, H]
            gv = cache.v[:, slot_idx, :ctx]
            if kvq:
                small = KVCache(gk, gv,
                                cache.k_scale[:, slot_idx, :ctx],
                                cache.v_scale[:, slot_idx, :ctx])
            else:
                small = KVCache(gk, gv)
            positions = starts[:, None] + jnp.arange(chunk)[None, :]
            logits, upd = forward(
                params, self.cfg, tokens, positions, small,
                starts, blockwise=True, write_mask=mask,
                pallas_int8=self.use_pallas_int8,
                pallas_int4=self.use_pallas_int4,
                logits_indices=last_idx)
            new_k = cache.k.at[:, slot_idx, :ctx].set(
                upd.k, mode="drop", unique_indices=True)
            new_v = cache.v.at[:, slot_idx, :ctx].set(
                upd.v, mode="drop", unique_indices=True)
            new_ks = new_vs = None
            if kvq:
                new_ks = cache.k_scale.at[:, slot_idx, :ctx].set(
                    upd.k_scale, mode="drop", unique_indices=True)
                new_vs = cache.v_scale.at[:, slot_idx, :ctx].set(
                    upd.v_scale, mode="drop", unique_indices=True)
            # First-token sampling fused into the same call: one device
            # round-trip per burst instead of two (TTFT-critical).
            rng, sub = jax.random.split(rng)
            firsts = sample_tokens(logits[:, 0, :self.sample_vocab], sub,
                                   temps, topks, topps,
                                   method=self.sampling_method)
            new_cur = cur.at[slot_idx].set(firsts, mode="drop")
            if replicate is not None:  # host-fetched on every DCN host
                firsts = jax.lax.with_sharding_constraint(firsts,
                                                          replicate)
            return KVCache(new_k, new_v, new_ks, new_vs), firsts, \
                new_cur, rng

        self._prefill_fns[key] = batched_prefill
        return batched_prefill

    def _get_paged_batched_prefill_fn(self, chunk: int, group: int,
                                      ctx: int):
        """Paged variant of ``_get_batched_prefill_fn``: the group's
        KV regions gather through per-row flat pool indices (read_idx
        [group, ctx]) instead of slot ids, and each row's written
        chunk scatters back through write_idx [group, chunk] (padding
        rows carry distinct out-of-range indices and drop). The
        forward body, rowcfg packing and fused first-token sampling
        are identical to the dense program."""
        key = ("pbatch", chunk, group, ctx)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        self._note_compile("batched_prefill", chunk=chunk, group=group,
                           ctx=ctx, kv_layout="paged",
                           **self._kvq_attrs)
        kvq = self.kv_quant

        @partial(jax.jit, donate_argnums=(1,))
        def paged_batched_prefill(params, cache: KVCache, tokens,
                                  rowcfg, read_idx, write_idx, cur,
                                  rng):
            slot_idx = rowcfg[:, 0].astype(jnp.int32)
            starts = rowcfg[:, 1].astype(jnp.int32)
            last_idx = rowcfg[:, 2].astype(jnp.int32)
            mask = rowcfg[:, 3] > 0.5
            temps, topks, topps = (rowcfg[:, 4],
                                   rowcfg[:, 5].astype(jnp.int32),
                                   rowcfg[:, 6])
            gk = cache.k[:, read_idx]  # [L, group, ctx, Kv, H]
            gv = cache.v[:, read_idx]
            if kvq:
                small = KVCache(gk, gv,
                                cache.k_scale[:, read_idx],
                                cache.v_scale[:, read_idx])
            else:
                small = KVCache(gk, gv)
            positions = starts[:, None] + jnp.arange(chunk)[None, :]
            logits, upd = forward(
                params, self.cfg, tokens, positions, small,
                starts, blockwise=True, write_mask=mask,
                pallas_int8=self.use_pallas_int8,
                pallas_int4=self.use_pallas_int4,
                logits_indices=last_idx)
            sel = positions  # [group, chunk] region rows each row wrote

            def written(arr):  # [L, group, ctx, ...] -> chunk rows
                idx = sel.reshape((1,) + sel.shape
                                  + (1,) * (arr.ndim - 3))
                return jnp.take_along_axis(arr, idx, axis=2)

            new_k = cache.k.at[:, write_idx].set(
                written(upd.k), mode="drop", unique_indices=True)
            new_v = cache.v.at[:, write_idx].set(
                written(upd.v), mode="drop", unique_indices=True)
            new_ks = new_vs = None
            if kvq:
                new_ks = cache.k_scale.at[:, write_idx].set(
                    written(upd.k_scale), mode="drop",
                    unique_indices=True)
                new_vs = cache.v_scale.at[:, write_idx].set(
                    written(upd.v_scale), mode="drop",
                    unique_indices=True)
            rng, sub = jax.random.split(rng)
            firsts = sample_tokens(logits[:, 0, :self.sample_vocab], sub,
                                   temps, topks, topps,
                                   method=self.sampling_method)
            new_cur = cur.at[slot_idx].set(firsts, mode="drop")
            return KVCache(new_k, new_v, new_ks, new_vs), firsts, \
                new_cur, rng

        self._prefill_fns[key] = paged_batched_prefill
        return paged_batched_prefill

    def _get_patch_fn(self):
        """One jitted program applying all dirty-slot mirror changes:
        packed [S, 9] = (dirty, position, active, temp, top_k, top_p,
        repeat_penalty, presence_penalty, frequency_penalty). Dirty
        slots also get their penalty-count row zeroed (a slot goes dirty
        exactly at (re)admission and completion — both are generation
        boundaries, and penalties are per-generation). Composes with
        in-flight calls (it consumes the latest chained arrays) without
        draining the pipeline, and costs one transfer + one program
        instead of per-field eager scatters."""
        if self._patch_fn is None:
            @partial(jax.jit, donate_argnums=(1,))
            def apply_patch(packed, counts, pos, active, temps, topks,
                            topps, reps, press, freqs):
                dirty = packed[:, 0] > 0.5
                pos = jnp.where(dirty, packed[:, 1].astype(pos.dtype), pos)
                active = jnp.where(dirty, packed[:, 2] > 0.5, active)
                temps = jnp.where(dirty, packed[:, 3], temps)
                topks = jnp.where(dirty, packed[:, 4].astype(topks.dtype),
                                  topks)
                topps = jnp.where(dirty, packed[:, 5], topps)
                reps = jnp.where(dirty, packed[:, 6], reps)
                press = jnp.where(dirty, packed[:, 7], press)
                freqs = jnp.where(dirty, packed[:, 8], freqs)
                counts = jnp.where(dirty[:, None], 0, counts)
                return counts, pos, active, temps, topks, topps, \
                    reps, press, freqs

            self._patch_fn = apply_patch
        return self._patch_fn

    def _get_sample_place_fn(self):
        """Jitted completion of a single-slot long prefill: split the
        rng, sample the first token from the chunk's last logits and
        scatter it into the current-token vector — one program, no
        eager ops."""
        if self._sample_place_fn is None:
            replicate = self._replicate_sharding()

            @jax.jit
            def sample_place(last_logits, cur, rng, cfg_row):
                slot = cfg_row[0].astype(jnp.int32)
                rng, sub = jax.random.split(rng)
                first = sample_tokens(
                    last_logits[None, :self.sample_vocab], sub,
                    cfg_row[1][None],
                    cfg_row[2].astype(jnp.int32)[None], cfg_row[3][None],
                    method=self.sampling_method)
                if replicate is not None:
                    first = jax.lax.with_sharding_constraint(first,
                                                             replicate)
                return first, cur.at[slot].set(first[0], mode="drop"), rng

            self._sample_place_fn = sample_place
        return self._sample_place_fn

    # ---------------- engine thread ----------------

    def _run(self) -> None:
        log.info("engine thread started",
                 model=self.cfg.name, slots=self.num_slots,
                 max_len=self.max_len)
        try:
            while True:
                # Watchdog heartbeat: one float store per iteration
                # (GIL-atomic, no lock). The loop iterates at least
                # every 50 ms when idle (command-queue timeout), so a
                # stale stamp means a blocked device call, not idleness.
                self._hb_mono = time.monotonic()
                if _fp.enabled:
                    # Chaos seam (docs/RESILIENCE.md): crash_thread or
                    # hang the engine thread itself — the supervisor-
                    # restart and watchdog drills inject here.
                    _fp.fire("engine.loop.tick")
                idle = not self._running and not self._inflight \
                    and not self._prefilling and not self._pending_firsts
                if not self._drain_commands(block=idle):
                    break
                if len(self._sched):
                    if not self._running and not self._inflight \
                            and not self._prefilling:
                        # Burst coalescing: from idle, the first request
                        # of a concurrent burst arrives a few ms before
                        # the rest, and admitting it alone would queue a
                        # full decode call ahead of everyone else's
                        # prefill (traced: +387 ms first-token for the
                        # stragglers). A 3 ms grace drains the rest of
                        # the burst into ONE admission group; a solo
                        # request pays +3 ms TTFT.
                        stop = False
                        for _ in range(2):
                            time.sleep(0.003)
                            if not self._drain_commands(block=False):
                                stop = True
                                break
                        if stop:
                            break
                    self._admit()
                if self._prefilling:
                    # One chunk per iteration: long prompts interleave
                    # with decode calls instead of stalling every
                    # running session for their whole prefill. Safe
                    # without draining the pipeline: chunk writes target
                    # reserved slots and are ordered behind in-flight
                    # calls by the cache data dependency.
                    self._advance_prefill()
                if self._pending_firsts:
                    # Emit any first tokens whose async fetch has landed;
                    # block when nothing else would make progress — which
                    # includes running requests whose whole remaining
                    # budget IS the pending first token (max_tokens=1):
                    # no decode call will ever be dispatched for those,
                    # so a non-blocking poll here would spin forever.
                    idle_wait = not self._inflight and not (
                        self._running and self._should_dispatch())
                    self._drain_firsts(block=idle_wait)
                if self._st_jf_pending and not self._inflight:
                    # Jump-forward fires only on an empty pipeline (the
                    # host FSM mirrors are then authoritative); while a
                    # jump is pending, dispatch pauses below so the
                    # pipeline drains within one retirement. If the
                    # chain evaporates (state moved on), decoding
                    # resumes untouched — the mask makes the decode
                    # steps emit the forced tokens correctly either
                    # way; jump-forward is purely the fast path.
                    self._st_jump_forward()
                if self._running:
                    if self._should_dispatch() \
                            and not self._st_jf_pending:
                        self._dispatch_decode()
                        if len(self._inflight) >= self.pipeline_depth:
                            self._retire_oldest()
                    elif self._inflight:
                        self._retire_oldest()
                elif self._inflight:
                    # Retire ONE call per iteration, not the whole
                    # pipeline: a new request arriving while the tail of
                    # a finished generation drains would otherwise wait
                    # pipeline_depth × call-time before admission (the
                    # command queue is only read between iterations).
                    self._retire_oldest()
                self._m_active.set(len(self._running))
                self._m_queue.set(len(self._sched)
                                  + len(self._prefilling))
                self._kv_tick()
        except (_fp.FaultCrash, Exception) as e:
            # The engine thread must not die silently. FaultCrash is a
            # BaseException (so it escapes every scoped handler like a
            # real interpreter-level fault would), but a crash HERE
            # must still terminal-event the in-flight requests and set
            # _stopped — the supervisor-restart path depends on it.
            log.critical(f"engine thread crashed: {e}", exc_info=True)
            if self.call_sink is not None:
                # A published descriptor may precede the crash: tell
                # followers the cluster is dead rather than leaving
                # them blocked in their recv loop (the prefill paths
                # publish their own aborts; this covers the
                # decode/spec/patch family and anything unforeseen).
                try:
                    self._sink("abort", reason=f"engine crashed: {e}")
                except Exception:
                    pass
            self._abort_all(f"engine crashed: {e}")
        else:
            self._abort_all("engine shut down")
        finally:
            self._stopped.set()
            log.info("engine thread stopped")

    def _abort_all(self, reason: str) -> None:
        """Terminal-event every outstanding request so no caller awaits
        forever after a stop or crash."""
        for req in list(self._by_id.values()):
            with self._term_lock:  # see _finish: atomic vs force_fail
                if req.finished:
                    continue
                req.finished = True
            if req.fsm is not None:
                # Unpin from the FSM arena (the abort path bypasses
                # _finish): a leaked ref would pin the schema's states
                # for the engine's lifetime.
                self._st_release(req)
            self._record_slo(req, ok=False)
            self._emit(req, {"type": "error", "error": reason,
                             "code": "internal_error"})
        self._by_id.clear()
        self._sched.clear()
        self._prefilling.clear()
        self._running.clear()
        self._inflight.clear()
        self._pending_firsts.clear()
        self._paged_leads.clear()
        self._st_jf_pending.clear()

    def _drain_commands(self, block: bool) -> bool:
        """Process queued commands. Returns False on stop."""
        while True:
            try:
                cmd, arg = self._commands.get(timeout=0.05 if block else 0.0)
            except queue.Empty:
                return True
            block = False
            if cmd == "stop":
                return False
            if cmd == "kick":
                pass  # submission landed in the scheduler; just wake
            elif cmd == "cancel":
                req = self._by_id.get(arg)
                if req is not None:
                    req.cancelled = True
                    if self._sched.cancel(arg) is not None:
                        # Still queued: terminal event now, O(1) (the
                        # r1 list did a linear remove scan here).
                        self._finish(req, "cancelled")
            elif cmd == "release":
                # The session is over (WS disconnect / end_session):
                # its parked host KV must go too, or the pool leaks
                # entries for sessions that can never return (they
                # would sit until TTL, squeezing live sessions out of
                # the budget).
                self._kv_pool.purge(arg)
                slot = self.slots.lookup(arg)
                if slot is not None and slot.active:
                    self._release_after.add(arg)
                else:
                    self.slots.release_session(arg)

    def _expire_queued(self, now: float | None = None) -> None:
        """Terminal-event every queued request past its deadline — they
        must never touch the TPU (ISSUE 2: predictable degradation; a
        request that already blew its latency budget serves nobody)."""
        now = time.monotonic() if now is None else now
        # No explicit now to the sweep: expiry must be judged on the
        # SCHEDULER's clock (injectable for deterministic race tests),
        # which set the deadlines in the first place. The engine-side
        # `now` below only formats the waited-time message/span.
        for entry in self._sched.take_expired():
            req = entry.payload
            if req is None or req.finished:
                continue
            waited = now - req.submitted_at
            if self._tracer.enabled:
                self._tracer.add_span(req.request_id, "queue_wait",
                                      req.submitted_at, now,
                                      priority=entry.priority,
                                      expired=True)
            self._finish(
                req, "error",
                error=f"request expired after {waited:.1f}s in the "
                f"admission queue (deadline "
                f"{entry.deadline - entry.submitted_at:.1f}s)",
                code="deadline_expired",
                retry_after=self._sched.retry_after())

    def _admit(self) -> None:
        """Move waiting requests into free slots.

        Admission order is the scheduler's: priority class (with bulk
        aging), round-robin across sessions, deadlines enforced. A
        request whose session is still generating is skipped in O(1)
        (rotated, not scanned) rather than head-of-line blocking.
        Requests whose remaining prompt fits one prefill bucket (the
        common chat-turn case) are prefetched together in one batched
        device call — a burst of N arrivals costs one prefill + one
        sample round-trip instead of 2N (the reference serialised
        engine-side prefills the same way it serialised everything: one
        HTTP request at a time).
        """
        self._expire_queued()
        # The batched path normally caps prompts at prefill_chunk so a
        # long prefill cannot stall running sessions (chunked path
        # interleaves instead). From IDLE there is nobody to stall, and
        # the chunked path would serialize a cold burst of long prompts
        # at one link round trip per chunk (measured: 16 × ~600-token
        # personas took 5 s p50 TTFT through it) — so allow one batched
        # call up to the 1024 bucket, which also lets intra-batch
        # prefix sharing engage on exactly the long-persona bursts
        # where it pays.
        idle = not self._running and not self._inflight \
            and not self._prefilling
        allowed = max(self.prefill_chunk, 1024) if idle \
            else self.prefill_chunk
        batch: list[tuple[_Request, Slot, int, list[int]]] = []
        busy = {s.session_id for s in self.slots.slots
                if s.active and s.session_id is not None}
        while True:
            entry = self._sched.pop(busy)
            if entry is None:
                break
            req = entry.payload
            if req.finished:
                # Already terminal (errored by _abort_all during a
                # crash before this pop saw it): admitting it would
                # leak a slot on a request nobody consumes.
                continue
            if req.cancelled:  # cancelled before the drain saw it
                self._finish(req, "cancelled")
                continue
            slot = self.slots.acquire(req.session_id)
            if slot is not None and self._kv_pool.enabled:
                # Admission proves the session is alive: clear any
                # released-tombstone so later parks aren't refused
                # (engine-seam callers reuse ids after release).
                self._kv_pool.revive(req.session_id)
            if slot is None:
                # All slots actively decoding: keep the entry at the
                # head of its session's queue (deadline intact).
                self._sched.requeue_front(entry)
                break
            # Re-acquiring a slot still visible in an in-flight call is
            # safe without draining: the donated cache chains every call,
            # so the old call's garbage writes (all at positions >= the
            # kept length > the reused prefix) execute strictly before
            # this slot's fresh prefill, whose writes then win; the old
            # call's tokens are dropped at retirement by the snapshot
            # ownership check.
            # Reserve immediately: activation is deferred to after the
            # batched prefill, and an unreserved slot would be fair game
            # for eviction by the next acquire in this same loop.
            req.slot = slot
            slot.active = True
            busy.add(req.session_id)  # one admission per session
            req.admitted_at = time.monotonic()
            self._m_queue_wait.observe(
                (req.admitted_at - req.submitted_at) * 1000)
            if self._tracer.enabled:
                self._tracer.add_span(req.request_id, "queue_wait",
                                      req.submitted_at, req.admitted_at,
                                      slot=slot.index,
                                      priority=entry.priority)
                self._tracer.set_phase(req.request_id, "prefill")
            prompt = req.prompt_tokens
            reused = self.slots.reuse_prefix(slot, prompt)
            if self.paged:
                # Reconcile the block table with the (possibly just
                # truncated) history: free divergent blocks, and COW a
                # shared tail block before any write can land in it.
                self._paged_sync_resident(slot)
                reused = min(reused, slot.kv_written)
            if reused:
                self._m_prefix.inc(reused)
            if not reused:
                # Radix prefix cache (kvcache/radix.py): alias the
                # longest cached block chain — zero device copies,
                # zero explicit registration. Defers internally to a
                # LONGER parked host entry (restore beats prefilling
                # the extra delta).
                reused = self._radix_admit(req, slot, prompt)
            if not reused and (restored := self._try_restore(req, slot,
                                                             prompt)):
                # Host-offload tier: the session's kept prefix came
                # back from host RAM — only the token delta prefills
                # below, composing with the delta path exactly like
                # slot-resident reuse.
                reused = restored
            if not reused and self.shared_prefix:
                # Fresh slot: stamp the longest prefix resident in any
                # OTHER slot (common system prompt across sessions)
                # instead of re-prefilling it, aligned to the 16-token
                # stamp granule (_stamp_prefix decomposes the share
                # into pow2 chunks, so the copy executable family
                # stays bounded without the old pow2 round-down that
                # wasted up to half the match). The source's rows
                # [0:share) are stable: its own writes only ever
                # target positions >= its kept length.
                src, share = self.slots.best_shared_prefix(slot, prompt)
                if self.paged:
                    # Paged tier: block ALIASING, not row copies — the
                    # full shared blocks refcount-bump into this slot's
                    # table, only a partial tail block device-copies
                    # (COW). No pow2 granule needed: there is no
                    # per-length executable family to bound.
                    aliased = self._paged_alias(src, slot, share)
                    if aliased:
                        slot.tokens = list(prompt[:aliased])
                        slot.kv_written = aliased
                        reused = aliased
                        self._m_shared.inc(aliased)
                    src = None  # the dense stamp below must not run
                if src is not None \
                        and share >= self._STAMP_GRANULE:
                    stamped = self._stamp_prefix(src.index, slot.index,
                                                 share)
                    if stamped:
                        slot.tokens = list(prompt[:stamped])
                        slot.kv_written = stamped
                        reused = stamped
                        self._m_shared.inc(stamped)
            todo = prompt[reused:]
            req.prefill_tokens = len(todo)  # restore-policy cost feed
            if reused + len(todo) > self.usable_len:
                self._finish(req, "error",
                             error=f"prompt ({len(prompt)} tok) exceeds "
                             "context")
                continue
            if self.paged and not self._paged_admissible(
                    slot, req, reused, len(todo)):
                continue  # shed with retry_after (blocks don't fit)
            if req.fsm is not None:
                # Constrained admission: pin the FSM into the device
                # arena, then take the single-slot prefill path — its
                # completion samples the first token under the start-
                # state mask (the batched group's fused sampler is
                # unmasked). Structured requests are the minority; the
                # batched path stays untouched for everyone else.
                try:
                    self._st_register(req)
                except ArenaFull as e:
                    self._finish(req, "error", error=str(e),
                                 code="structured_capacity")
                    continue
                self._prefilling.append(
                    _PrefillState(req=req, slot=slot, start=reused,
                                  todo=todo))
                continue
            bucket = next((b for b in _PREFILL_BUCKETS if b >= len(todo)),
                          None)
            if bucket is not None and len(todo) <= allowed \
                    and reused + bucket <= self.max_len \
                    and not req.params.prefill_only \
                    and not self._ring_prefill_eligible(reused,
                                                        len(todo)):
                batch.append((req, slot, reused, todo))
            else:
                # Long prompts — and, on an sp>1 mesh, fresh prompts
                # past one chip's KV shard (ring-eligible) — go through
                # _advance_prefill.
                self._prefilling.append(
                    _PrefillState(req=req, slot=slot, start=reused,
                                  todo=todo))
        if batch:
            if self.shared_prefix and len(batch) >= 2:
                self._prefill_batched_shared(batch)
            else:
                self._prefill_batched(batch)
        # Entries the pop loop found expired must terminal-event NOW:
        # diverting the last queued entry drops the queue to empty, so
        # no later loop iteration would re-enter _admit to drain them.
        self._expire_queued()

    def _advance_prefill(self) -> None:
        """Run ONE chunk of the oldest in-progress long prefill."""
        # Sweep the WHOLE queue for cancelled/finished entries — a
        # cancel must free its reserved slot and emit its terminal event
        # immediately, not after every earlier long prefill completes.
        keep: list[_PrefillState] = []
        for st in self._prefilling:
            if st.req.finished:
                continue
            if st.req.cancelled:
                self._finish(st.req, "cancelled")
                continue
            keep.append(st)
        self._prefilling = keep
        if not self._prefilling:
            return
        st = self._prefilling[0]
        req, slot = st.req, st.slot
        try:
            if _fp.enabled:
                # Chaos seam: `error` is scoped to this request by the
                # handler below (the engine survives); `crash_thread`
                # escapes it (BaseException) and kills the thread.
                _fp.fire("engine.prefill.dispatch",
                         request_id=req.request_id,
                         session_id=req.session_id)
            ring_bucket = self._ring_prefill_eligible(st.start,
                                                      len(st.todo))
            t0p = time.monotonic()
            if ring_bucket:
                # Whole prompt in ONE ring-attention call: per-chip
                # attention memory O(T/sp) instead of the all-gather
                # form (see _get_ring_prefill_fn).
                n = len(st.todo)
                padded = np.zeros((ring_bucket,), np.int32)
                padded[:n] = st.todo
                fn = self._get_ring_prefill_fn(ring_bucket)
                self._sink("ring_prefill", bucket=ring_bucket,
                           tokens=padded, slot=slot.index, last=n - 1)
                self.cache, st.last_logits = fn(
                    self.params, self.cache, self._arg(padded),
                    np.int32(slot.index), np.int32(n - 1))
                slot.tokens.extend(st.todo)
                st.start = n
                slot.kv_written = n
                st.todo = []
                self._tracer.step(
                    "engine_prefill", t0p, time.monotonic(),
                    bucket=ring_bucket, tokens=n, rows=ring_bucket,
                    kind="ring",
                    program=program_key("ring_prefill",
                                        bucket=ring_bucket),
                    flops=self._perf.call_flops(n, n))
            else:
                take = min(len(st.todo), self.prefill_chunk)
                bucket = next(b for b in _PREFILL_BUCKETS if b >= take)
                # A padded bucket must not extend past the cache end —
                # dynamic_update_slice would clamp the start and corrupt
                # earlier rows. Shrink the chunk until its bucket fits.
                while st.start + bucket > self.max_len and take > 1:
                    bucket //= 2
                    take = min(take, bucket)
                if st.start + bucket > self.max_len:
                    self._prefilling.pop(0)
                    self._finish(req, "error",
                                 error="KV cache exhausted during "
                                       "prefill")
                    return
                if self.paged and not self._kv_blocks.ensure(
                        slot.index, st.start + bucket):
                    # The rehearsed mid-prefill exhaustion: shed THIS
                    # request with retry_after and exact accounting
                    # (ensure is all-or-nothing), never crash the
                    # engine (kv.block_alloc chaos drill).
                    self._prefilling.pop(0)
                    self._paged_exhausted_finish(
                        req, "KV block pool exhausted during prefill")
                    return
                chunk = st.todo[:take]
                padded = np.zeros((bucket,), np.int32)
                padded[:take] = chunk
                self._sink("prefill", bucket=bucket, tokens=padded,
                           start=st.start, slot=slot.index,
                           last=take - 1)
                # numpy scalars, not jnp ones: each eager jnp scalar is
                # its own device round trip on relayed backends.
                prog = self._prefill_program(st.start, bucket)
                st.last_logits = self._run_chunk_prefill(
                    slot, padded, st.start, take - 1, bucket)
                slot.tokens.extend(chunk)
                st.start += take
                slot.kv_written = st.start
                st.todo = st.todo[take:]
                # Attribution: one padded-bucket chunk (rows computed =
                # the bucket; useful = the chunk) against the KV
                # horizon it attended. The interval covers dispatch —
                # the device compute overlaps later step records.
                self._tracer.step(
                    "engine_prefill", t0p, time.monotonic(),
                    bucket=bucket, tokens=take, rows=bucket,
                    kind="chunk", program=prog,
                    flops=self._perf.call_flops(take, st.start))
            # Each completed chunk is forward progress — for EVERY
            # request in the prefill FIFO, not just the head: the ones
            # queued behind it are advancing toward service, and
            # counting their wait as "no progress" would let the
            # watchdog force-fail healthy requests behind one long
            # prompt.
            now = time.monotonic()
            for waiting in self._prefilling:
                waiting.req.last_progress_at = now
            if st.todo:
                return  # next chunk on a later iteration
            self._prefilling.pop(0)
            self._m_prefill.observe((time.monotonic() - st.t0) * 1000)
            if req.params.prefill_only:
                # Disaggregated prefill tier: the prompt's KV is
                # written — park it to the host pool and finish
                # WITHOUT sampling or activating (zero decode-slot
                # occupancy; the router migrates the parked entry to
                # a decode replica, router/disagg.py).
                self._prefill_park_finish(req, slot)
                return
            if req.fsm is not None:
                # Masked first-token sample from the FSM start state;
                # also activates — _st_sample_place defers the fetch
                # like the plain path below.
                self._activate(req, slot)
                self._st_sample_place(req, slot, st.last_logits)
                return
            cfg_row = np.array([slot.index, req.params.temperature,
                                req.params.top_k, req.params.top_p],
                               np.float32)
            self._sink("sample_place", cfg_row=cfg_row)
            first, self._cur_tokens, self._rng_dev = \
                self._get_sample_place_fn()(
                    st.last_logits, self._cur_tokens, self._rng_dev,
                    self._arg(cfg_row))
            self._activate(req, slot)
            self._defer_first(first, [(0, slot.index, req)])
        except Exception as e:
            log.error(f"prefill failed for {req.request_id}: {e}",
                      exc_info=True)
            if self.call_sink is not None:
                # A dispatch error AFTER a published descriptor means
                # per-host device state may have diverged: scoping the
                # error to one request would serve a corrupted cluster.
                # Abort followers and escalate (engine thread →
                # _abort_all; multi-host recovery = cluster restart).
                self._sink("abort", reason=str(e))
                raise
            if self._prefilling and self._prefilling[0] is st:
                self._prefilling.pop(0)
            self._finish(req, "error", error=str(e))

    # Intra-batch sharing engages only when the common prefix is at
    # least this long: below it, the extra prefill wave + copy
    # dispatches cost more than the recompute they save (a share has to
    # move the delta into a SMALLER prefill bucket to win).
    _INTRA_SHARE_MIN = 64

    def _prefill_batched_shared(
            self, batch: list[tuple[_Request, Slot, int, list[int]]]) -> None:
        """Intra-batch shared prefix: when several FRESH admissions of
        one burst share a long leading prefix (a fleet of sessions with
        one system prompt arriving together), prefill the longest-
        prompt leader in a first wave, stamp the shared rows onto the
        other slots by device copy, and batch-prefill only their
        deltas — burst prefill compute drops from N×full toward
        1×full + N×delta."""
        from fasttalk_tpu.engine.slots import _lcp

        fresh = [item for item in batch if item[2] == 0]
        members: list[tuple[tuple, int]] = []
        if len(fresh) >= 2:
            leader = max(fresh, key=lambda it: len(it[0].prompt_tokens))
            lp = leader[0].prompt_tokens
            for item in fresh:
                if item is leader:
                    continue
                pt = item[0].prompt_tokens
                share = _lcp(lp, pt, min(len(lp), len(pt) - 1))
                share -= share % self._STAMP_GRANULE
                if share < self._INTRA_SHARE_MIN:
                    continue
                # Sharing must actually shrink the member's prefill
                # bucket (else two serialized waves + copies are
                # strictly slower than the one batched wave), and the
                # delta bucket must still fit the cache at its new
                # start (the admission guard checked start=0; a clamped
                # out-of-range write start would silently corrupt KV).
                full_b = next(b for b in _PREFILL_BUCKETS
                              if b >= len(pt))
                delta_b = next(b for b in _PREFILL_BUCKETS
                               if b >= max(1, len(pt) - share))
                if delta_b < full_b and share + delta_b <= self.max_len:
                    members.append((item, share))
        if not members:
            self._prefill_batched(batch)
            return
        member_ids = {id(it) for it, _ in members}
        self._prefill_batched([it for it in batch
                               if id(it) not in member_ids])
        lreq, lslot = leader[0], leader[1]
        second: list[tuple[_Request, Slot, int, list[int]]] = []
        for (req, slot, _reused, _todo), share in members:
            if req.finished:
                continue
            # Re-clamp against what the leader actually wrote (its
            # prefill may have errored and finished the request) — and
            # re-check the delta-bucket fit, since a SMALLER share
            # means a LARGER delta whose bucket may no longer fit at
            # the new start.
            share = min(share, lslot.kv_written)
            share -= share % self._STAMP_GRANULE
            delta_b = next(
                (b for b in _PREFILL_BUCKETS
                 if b >= max(1, len(req.prompt_tokens) - share)), None)
            if lreq.finished or share < self._INTRA_SHARE_MIN \
                    or delta_b is None \
                    or share + delta_b > self.max_len:
                second.append((req, slot, 0, req.prompt_tokens))
                continue
            share = self._stamp_prefix(lslot.index, slot.index, share)
            slot.tokens = list(req.prompt_tokens[:share])
            slot.kv_written = share
            self._m_shared.inc(share)
            second.append((req, slot, share, req.prompt_tokens[share:]))
        if second:
            self._prefill_batched(second)

    def _prefill_batched(
            self, batch: list[tuple[_Request, Slot, int, list[int]]]) -> None:
        """Prefill several single-bucket prompts in one device call per
        (bucket, group-size) shape: gather the target slots' KV rows,
        run one batched forward, scatter the rows back, then sample every
        first token in a single batched call."""
        t0 = time.monotonic()
        by_bucket: dict[int, list] = {}
        for item in batch:
            bucket = next(b for b in _PREFILL_BUCKETS
                          if b >= max(1, len(item[3])))
            by_bucket.setdefault(bucket, []).append(item)
        for bucket, group in sorted(by_bucket.items()):
            while group:
                sub, group = group[:self.num_slots], group[self.num_slots:]
                try:
                    self._prefill_group(bucket, sub)
                except Exception as e:
                    log.error(f"batched prefill failed: {e}", exc_info=True)
                    if self.call_sink is not None:
                        # See _advance_prefill: a post-publish dispatch
                        # error must abort the cluster, not be scoped.
                        self._sink("abort", reason=str(e))
                        raise
                    # Scoped to this device call: requests in other
                    # groups (possibly already activated and streaming)
                    # are untouched.
                    for req, _, _, _ in sub:
                        self._finish(req, "error", error=str(e))
        self._m_prefill.observe((time.monotonic() - t0) * 1000)

    def _prefill_group(self, bucket: int,
                       sub: list[tuple[_Request, Slot, int, list[int]]],
                       ) -> None:
        """One batched prefill device call + one batched first-token
        sample for a same-bucket group of requests."""
        if _fp.enabled:
            # Same seam name as the chunked path: _prefill_batched's
            # handler scopes an `error` to this group's requests.
            _fp.fire("engine.prefill.dispatch",
                     request_id=";".join(r.request_id
                                         for r, _, _, _ in sub))
        if self.paged:
            # Blocks for every row's padded write horizon, before any
            # array is built: a row the pool cannot hold sheds HERE
            # with retry_after (exact accounting — ensure is
            # all-or-nothing) and the rest of the group proceeds.
            kept = []
            for item in sub:
                if self._kv_blocks.ensure(item[1].index,
                                          item[2] + bucket):
                    kept.append(item)
                else:
                    self._paged_exhausted_finish(
                        item[0], "KV block pool exhausted during "
                                 "batched prefill")
            sub = kept
            if not sub:
                return
        g = len(sub)
        # Only two group shapes ever compile per bucket: 1 and num_slots.
        # A mid-size burst pads to the full batch (the padded rows are
        # masked) — wasted FLOPs are bounded and tiny next to the cost of
        # compiling per burst size.
        gp = 1 if g == 1 else self.num_slots
        tokens = np.zeros((gp, bucket), np.int32)
        rowcfg = np.zeros((gp, 7), np.float32)
        # Padding rows scatter out of range (mode="drop"); each gets a
        # distinct index so unique_indices holds.
        rowcfg[:, 0] = np.arange(self.num_slots,
                                 self.num_slots + gp, dtype=np.float32)
        for j, (req, slot, start, todo) in enumerate(sub):
            tokens[j, :len(todo)] = todo
            rowcfg[j] = (slot.index, start, len(todo) - 1, 1.0,
                         req.params.temperature, req.params.top_k,
                         req.params.top_p)
        # Gather only as much of each slot row as this chunk can touch,
        # rounded to a KV bucket so the shape set stays small.
        need = int(rowcfg[:, 1].max()) + bucket
        ctx = next((b for b in _KV_BUCKETS
                    if b >= need and b <= self.max_len), self.max_len)
        self._sink("batched_prefill", bucket=bucket, gp=gp, ctx=ctx,
                   tokens=tokens, rowcfg=rowcfg)
        # First tokens stay on device: the program scatters them into
        # the decode chain's current-token vector, and the host copy is
        # async — the engine thread dispatches the first decode call
        # without waiting for the round trip; text is emitted when the
        # fetch lands.
        t0p = time.monotonic()
        if self.paged:
            read_idx = np.zeros((gp, ctx), np.int32)
            write_idx = np.zeros((gp, bucket), np.int32)
            for j in range(gp):
                if j < len(sub):
                    slot_j, start_j = sub[j][1], sub[j][2]
                    read_idx[j] = self._paged_read_indices(
                        slot_j.index, ctx)
                    write_idx[j] = self._paged_write_indices(
                        slot_j.index, start_j, bucket)
                else:
                    write_idx[j] = self._paged_oob_indices(j, bucket)
            fn = self._get_paged_batched_prefill_fn(bucket, gp, ctx)
            (self.cache, firsts_dev, self._cur_tokens,
             self._rng_dev) = fn(
                self.params, self.cache, self._arg(tokens),
                self._arg(rowcfg), self._arg(read_idx),
                self._arg(write_idx), self._cur_tokens, self._rng_dev)
        else:
            fn = self._get_batched_prefill_fn(bucket, gp, ctx)
            (self.cache, firsts_dev, self._cur_tokens,
             self._rng_dev) = fn(
                self.params, self.cache, self._arg(tokens),
                self._arg(rowcfg), self._cur_tokens, self._rng_dev)
        # Attribution row: the call computed gp × bucket token rows
        # (padding rows + per-row bucket padding included); useful =
        # the real prompt tokens. Interval covers dispatch only — the
        # device compute overlaps the following step records.
        real = sum(len(todo) for _, _, _, todo in sub)
        self._tracer.step(
            "engine_prefill", t0p, time.monotonic(), bucket=bucket,
            tokens=real, rows=gp * bucket, kind="batched", group=g,
            program=program_key(
                "batched_prefill", chunk=bucket, group=gp, ctx=ctx,
                **self._kvq_attrs,
                **({"kv_layout": "paged"} if self.paged else {})),
            flops=self._perf.call_flops(real, ctx))
        entries = []
        for j, (req, slot, start, todo) in enumerate(sub):
            slot.tokens.extend(todo)
            slot.kv_written = start + len(todo)
            self._activate(req, slot)
            entries.append((j, slot.index, req))
        self._defer_first(firsts_dev, entries)

    def _should_dispatch(self) -> bool:
        """Dispatch another K-step call only if some running request can
        still use tokens beyond what in-flight calls already promise it.

        Without this cap the dispatcher runs pipeline_depth calls past
        every generation's end; those stale calls hold the (in-order)
        device queue and the NEXT request's prefill — and therefore its
        first token — waits behind all of them. A length-capped
        generation now finishes with an empty pipeline."""
        if self._pending_firsts and self._running and all(
                req.first_pending for req in self._running.values()):
            # Pure admission burst: EVERY running request is still
            # waiting for its prefill-sampled first token. A decode
            # dispatch now would enter the in-order device stream ahead
            # of the firsts fetch and push first-token latency a whole
            # call's compute later (traced: +150 ms at 32 steps on the
            # relayed attach, scripts/profile_ttft.py). Hold off; the
            # loop blocks on the fetch and decode follows one link
            # round trip later. Steady state is untouched — any request
            # past its first token makes this condition false.
            return False
        promised: dict[int, int] = {}
        for _, min_toks, _, snap, _, _, _ in self._inflight:
            for _, req in snap:
                promised[id(req)] = promised.get(id(req), 0) + min_toks
        # A first token whose fetch hasn't landed is not yet counted in
        # req.generated but will be — ignoring it over-dispatches one
        # whole stale call at exact-budget boundaries.
        return any(
            req.params.max_tokens - req.generated
            - (1 if req.first_pending else 0) > promised.get(id(req), 0)
            for req in self._running.values())

    def _activate(self, req: _Request, slot: Slot) -> None:
        """Mark a freshly prefilled slot as decoding. The first sampled
        token is already on the device (scattered into the decode
        chain's current-token vector by the caller); its text is emitted
        by _drain_firsts when the async fetch lands."""
        s = slot.index
        slot.active = True
        req.slot = slot
        req.decode_started_at = time.monotonic()
        req.last_progress_at = req.decode_started_at
        if req.admitted_at is not None:
            self._m_prefill_req.observe(
                (req.decode_started_at - req.admitted_at) * 1000)
            if req.prefill_tokens:
                # Measured prefill throughput → the restore policy's
                # cost model (admission-to-activation covers the same
                # dispatch overheads a restore competes against).
                self._kv_policy.note_prefill(
                    req.prefill_tokens,
                    req.decode_started_at - req.admitted_at)
            if self._tracer.enabled:
                self._tracer.add_span(
                    req.request_id, "prefill", req.admitted_at,
                    req.decode_started_at, slot=s,
                    prompt_tokens=len(req.prompt_tokens))
                self._tracer.set_phase(req.request_id, "decode")
        self._running[s] = req
        if req.fsm_entry is not None:
            # The slot's row into the arena's per-FSM class table —
            # shipped with every constrained decode call.
            self._st_sel[s] = req.fsm_entry.sel
        self._positions[s] = len(slot.tokens)
        self._active_mask[s] = True
        self._temps[s] = req.params.temperature
        self._topks[s] = req.params.top_k
        self._topps[s] = req.params.top_p
        self._reps[s] = req.params.repeat_penalty
        self._press[s] = req.params.presence_penalty
        self._freqs[s] = req.params.frequency_penalty
        self._dirty_slots.add(s)
        if self.spec_draft:
            self._dirty_history[s] = list(slot.tokens)

    def _defer_first(self, firsts_dev: Any, entries: list) -> None:
        """Queue first sampled tokens for emission once their
        device→host copy (started here, on a worker) completes."""
        for _, _, req in entries:
            req.first_pending = True
        self._pending_firsts.append(
            (self._fetch(firsts_dev), entries))

    def _drain_firsts(self, block: bool) -> None:
        """Emit first tokens whose fetch has landed (all of them when
        ``block``). Entry guards mirror _retire_oldest: a request that
        finished (cancel, error) before its first token arrived drops
        it."""
        while self._pending_firsts:
            fut, entries = self._pending_firsts[0]
            if not block and not fut.done():
                return
            self._pending_firsts.popleft()
            self._j_wait0 = time.monotonic()
            arr = fut.result()
            self._j_fetched = time.monotonic()
            for j, s, req in entries:
                req.first_pending = False
                if req.finished or self._running.get(s) is not req:
                    continue
                self._consume_token(req, int(arr[j]))
                self._flush_emit(req)

    def _get_hist_patch_fn(self, row_len: int | None = None):
        """Jitted history-row upload for speculative decoding: rows of
        freshly admitted slots replace their history rows wholesale
        (out-of-range slot indices in the padded batch drop).

        ``row_len`` buckets the HOST-SIDE upload: shipping full
        [S, max_len] rows cost 512 KB through the relay per admission
        wave (measured as most of auto-spec's bench overhead once it
        became the default) when the prompts being uploaded are ~100
        tokens. The program pads to max_len on device — HBM-local and
        free next to the link transfer it replaces."""
        row_len = self.max_len if row_len is None else row_len
        fn = self._hist_patch_fns.get(row_len)
        if fn is None:
            @partial(jax.jit, donate_argnums=(0,))
            def apply_hist(hist, rows, slots):
                full = jnp.zeros((rows.shape[0], self.max_len),
                                 rows.dtype)
                full = jax.lax.dynamic_update_slice(full, rows, (0, 0))
                return hist.at[slots].set(full, mode="drop",
                                          unique_indices=True)

            self._hist_patch_fns[row_len] = apply_hist
            fn = apply_hist
        return fn

    def _patch_slot_state(self) -> None:
        """Apply dirty host mirrors onto the chained device arrays via
        one jitted program and one packed transfer.

        In-flight calls are untouched — safe because their snapshots
        drop tokens of finished requests at retirement, and a freed
        slot's fresh prefill is ordered after any in-flight garbage
        writes by the donated-cache data dependency (see _admit).
        Every later dispatch sees the patched state. This replaces the
        old flush-the-pipeline-and-reupload on every slot-set change,
        which serialised admission behind up to pipeline_depth decode
        calls."""
        if self._st_dirty:
            # FSM-state resets/refreshes (finish → FREE, arena repack):
            # a separate tiny program so the shared patch executable —
            # and therefore the unconstrained serving path — stays
            # byte-identical to the pre-structured engine.
            packed = np.zeros((self.num_slots, 2), np.float32)
            for s in self._st_dirty:
                packed[s] = (1.0, self._st_global_state(s))
            self._st_dirty.clear()
            self._st_state_dev = self._get_st_patch_fn()(
                self._arg(packed), self._st_state_dev)
        if self.spec_draft and self._dirty_history:
            # Prompt tokens of freshly admitted slots -> device history
            # (one bucketed upload + one program that pads to max_len
            # on device; the sampled tokens appended later are
            # maintained in-program).
            longest = max((len(t) for t in
                           self._dirty_history.values()), default=1)
            rb = min(self.max_len,
                     max(256, 1 << (longest - 1).bit_length()))
            rows = np.zeros((self.num_slots, rb), np.int32)
            slots = np.full((self.num_slots,), self.num_slots, np.int32)
            for i, (s, tokens) in enumerate(self._dirty_history.items()):
                rows[i, :min(len(tokens), rb)] = tokens[:rb]
                slots[i] = s
            self._dirty_history.clear()
            self._sink("hist_patch", rb=rb, rows=rows, slots=slots)
            self._history_dev = self._get_hist_patch_fn(rb)(
                self._history_dev, self._arg(rows), self._arg(slots))
        if not self._dirty_slots:
            return
        packed = np.zeros((self.num_slots, 9), np.float32)
        for s in self._dirty_slots:
            packed[s] = (1.0, self._positions[s], self._active_mask[s],
                         self._temps[s], self._topks[s], self._topps[s],
                         self._reps[s], self._press[s], self._freqs[s])
        self._dirty_slots.clear()
        self._sink("patch", packed=packed)
        (self._counts_dev, self._positions_dev, self._active_dev,
         self._temps_dev, self._topks_dev, self._topps_dev,
         self._reps_dev, self._press_dev, self._freqs_dev) = \
            self._get_patch_fn()(
                self._arg(packed), self._counts_dev, self._positions_dev,
                self._active_dev, self._temps_dev, self._topks_dev,
                self._topps_dev, self._reps_dev, self._press_dev,
                self._freqs_dev)

    def _spec_call_wanted(self) -> bool:
        """Per-call speculative/plain decision. "ngram": always spec.
        "auto": spec while the measured EMA tokens-per-verify clears
        the break-even (a verify block costs ~spec_breakeven plain
        steps); below it, plain calls with a periodic probe so the EMA
        tracks workload shifts — acceptance recovers (templated or
        repetitive text arrives) and auto re-engages within one probe
        period."""
        if self.spec_mode == "ngram":
            return True
        if self._spec_ema >= self.spec_breakeven:
            return True
        self._spec_probe_countdown -= 1
        if self._spec_probe_countdown <= 0:
            self._spec_probe_countdown = self._spec_probe_every
            return True
        return False

    def _dispatch_decode(self) -> None:
        """Launch one K-step decode call; does not wait for results."""
        if _fp.enabled:
            # Chaos seam: an `error` here is a dispatch-path failure —
            # it propagates to _run's crash handler (terminal events
            # for every request, supervisor restart), exactly like a
            # real XLA dispatch fault. Host-side only: the jitted
            # decode program itself is byte-identical with or without
            # fault injection.
            _fp.fire("engine.decode.dispatch")
        worst_adv = self.steps_per_call * (self.spec_draft + 1
                                           if self.spec_draft else 1)
        if self.paged and not self._paged_prepare_decode(worst_adv):
            return  # every running request was shed for blocks
        self._patch_slot_state()
        t_disp = time.monotonic()
        active = list(self._running)
        snapshot = list(self._running.items())
        # Short calls while admissions/prefills are pending or a first
        # token's fetch is still in flight (anything TTFT-critical waits
        # behind the in-order device queue); long calls in steady state
        # (amortise the per-call cache boundary copy).
        steps = (self.steps_burst if len(self._sched) or self._prefilling
                 or any(req.first_pending
                        for req in self._running.values())
                 else self.steps_per_call)
        # Device positions lead the host mirrors by the in-flight calls'
        # maximum advances; size the KV bucket for where the device can
        # be at the END of this call.
        base = int(self._positions[active].max()) \
            + sum(adv for _, _, adv, _, _, _, _ in self._inflight)
        # Constrained slot running → the per-call compat matrix
        # (docs/STRUCTURED.md): speculative calls pause (verify-block
        # masking is unvalidated in v1) and the fsm decode variants
        # carry the per-slot FSM state + union tables. With NO
        # constrained slot this block is untouched and the original
        # executables dispatch — the zero-cost-when-off guarantee.
        st_on = any(r.fsm is not None for r in self._running.values())
        T = self.spec_draft + 1
        if self.spec_draft and not st_on and self._spec_call_wanted():
            # Size the KV bucket by the EMA-EXPECTED advance (+1 block
            # of headroom), not the K*T worst case: worst-case sizing
            # jumped to the next bucket immediately — a mid-stream
            # compile (~0.4 s traced) and doubled attention reads for
            # advances that almost never happen. Underestimates are
            # SAFE: the in-call act gate (pos + T <= kv_len) makes a
            # slot sit out steps that would overflow the bucket, the
            # under-delivery shows up in the retired n_out, and the
            # host's position mirrors re-size the next call.
            exp_adv = int(steps * min(float(T),
                                      max(1.0, self._spec_ema) + 1.0))
            # The bucket must leave at least one FULL verify block of
            # headroom past every slot's worst-case position, or the
            # in-call act gate masks every step and the call makes no
            # progress — with mirrors never advancing, the identical
            # no-op call would be re-dispatched forever (livelock;
            # reachable when T > exp_adv near a bucket edge).
            need = base + max(exp_adv, T)
            if need <= self.max_len:
                kv_len = next((b for b in _KV_BUCKETS
                               if b >= need and b <= self.max_len),
                              self.max_len)
                fn = self._get_spec_decode_fn(kv_len, steps)
                self._sink("spec", kv_len=kv_len, steps=steps)
                (self.cache, self._history_dev, self._counts_dev, toks,
                 self._cur_tokens, self._positions_dev,
                 self._rng_dev) = fn(
                    self.params, self.cache, self._history_dev,
                    self._counts_dev, self._cur_tokens,
                    self._positions_dev, self._active_dev,
                    self._temps_dev, self._topks_dev, self._topps_dev,
                    self._reps_dev, self._press_dev, self._freqs_dev,
                    self._rng_dev, *self._paged_decode_args(kv_len))
                if self.paged:
                    self._paged_leads.append(worst_adv)
                # Promise the EMA-expected tokens, not the minimum:
                # spec calls deliver K..K*T, and promising K made the
                # dispatcher queue up to T× too many calls — a
                # stale-call tail holding the in-order device queue for
                # seconds (traced).
                promise = steps * min(float(T),
                                      max(1.0, self._spec_ema))
                self._inflight.append(
                    (self._fetch(toks), promise,
                     exp_adv, snapshot, t_disp, kv_len,
                     program_key("spec_decode", kv_len=kv_len,
                                 steps=steps)))
                return
        max_pos = base + steps
        kv_len = next((b for b in _KV_BUCKETS
                       if b >= max_pos and b <= self.max_len), self.max_len)
        if self.spec_draft:
            # Auto mode chose plain for this call (or the spec bucket
            # check fell through): keep the draft history fresh so the
            # next probe drafts from current text, not stale history.
            fn = self._get_decode_fn(kv_len, steps, with_history=True,
                                     with_fsm=st_on)
            self._sink("decode", kv_len=kv_len, steps=steps,
                       with_history=True)
            if st_on:
                (self.cache, self._history_dev, self._counts_dev,
                 self._st_state_dev, toks, self._cur_tokens,
                 self._positions_dev, self._rng_dev) = fn(
                    self.params, self.cache, self._history_dev,
                    self._counts_dev, self._st_state_dev,
                    self._cur_tokens, self._positions_dev,
                    self._active_dev, self._temps_dev, self._topks_dev,
                    self._topps_dev, self._reps_dev, self._press_dev,
                    self._freqs_dev, self._rng_dev,
                    self._arg(self._st_sel.copy()),
                    self._st_masks_dev, self._st_cls_dev,
                    self._st_nexts_dev,
                    *self._paged_decode_args(kv_len))
            else:
                (self.cache, self._history_dev, self._counts_dev, toks,
                 self._cur_tokens, self._positions_dev,
                 self._rng_dev) = fn(
                    self.params, self.cache, self._history_dev,
                    self._counts_dev, self._cur_tokens,
                    self._positions_dev, self._active_dev,
                    self._temps_dev, self._topks_dev, self._topps_dev,
                    self._reps_dev, self._press_dev, self._freqs_dev,
                    self._rng_dev, *self._paged_decode_args(kv_len))
            if self.paged:
                self._paged_leads.append(worst_adv)
            self._inflight.append(
                (self._fetch(toks), steps, steps,
                 snapshot, t_disp, kv_len,
                 self._decode_program(kv_len, steps, st_on)))
            return
        fn = self._get_decode_fn(kv_len, steps, with_fsm=st_on)
        self._sink("decode", kv_len=kv_len, steps=steps,
                   with_history=False)
        if st_on:
            (self.cache, self._counts_dev, self._st_state_dev, toks,
             self._cur_tokens, self._positions_dev, self._rng_dev) = fn(
                self.params, self.cache, self._counts_dev,
                self._st_state_dev, self._cur_tokens,
                self._positions_dev, self._active_dev, self._temps_dev,
                self._topks_dev, self._topps_dev, self._reps_dev,
                self._press_dev, self._freqs_dev, self._rng_dev,
                self._arg(self._st_sel.copy()), self._st_masks_dev,
                self._st_cls_dev, self._st_nexts_dev,
                *self._paged_decode_args(kv_len))
        else:
            (self.cache, self._counts_dev, toks, self._cur_tokens,
             self._positions_dev, self._rng_dev) = fn(
                self.params, self.cache, self._counts_dev,
                self._cur_tokens, self._positions_dev, self._active_dev,
                self._temps_dev, self._topks_dev, self._topps_dev,
                self._reps_dev, self._press_dev, self._freqs_dev,
                self._rng_dev, *self._paged_decode_args(kv_len))
        if self.paged:
            self._paged_leads.append(worst_adv)
        # Start the device→host copy NOW on a worker thread: by
        # retirement time it has been in flight for a whole call's
        # compute, and later calls' fetches overlap it (see the
        # _fetch_pool note in __init__).
        self._inflight.append(
            (self._fetch(toks), steps, steps,
             snapshot, t_disp, kv_len,
             self._decode_program(kv_len, steps, st_on)))

    def _retire_oldest(self) -> None:
        """Block on the oldest in-flight call and consume its tokens."""
        (fut, _, _, snapshot, t_disp, kv_len,
         program) = self._inflight.popleft()
        if self.paged and self._paged_leads:
            self._paged_leads.popleft()
        if _fp.enabled:
            # Chaos seam: `hang` here is the wedged-device-call
            # scenario — the heartbeat goes stale and the watchdog
            # must detect it and force_fail the stalled requests.
            _fp.fire("engine.retire.fetch")
        gen_before = {id(req): req.generated for _, req in snapshot} \
            if self._tracer.enabled else {}
        if any(req.first_pending for _, req in snapshot):
            # A request in this call still awaits its first token:
            # emit firsts before any of its decode tokens (the firsts
            # copy was issued earlier and overlaps this call's fetch on
            # the worker pool, so this wait is bounded).
            self._drain_firsts(block=True)
        t0 = time.monotonic()
        res = fut.result()  # sync point
        self._j_wait0 = t0
        self._j_fetched = time.monotonic()
        self._m_step.observe((self._j_fetched - t0) * 1000)
        # The block above gave every pending firsts-copy >= one call's
        # wall time to land: emit whatever arrived NOW. Without this, a
        # request admitted after call N dispatched waits for call N+1's
        # retirement (whose snapshot it is in) — burst admissions saw
        # their first tokens staggered one ~140 ms retirement per
        # admission group (measured: WS-burst p50 TTFT 412 ms engine-side
        # vs 166 ms when all requests land in one group).
        if self._pending_firsts:
            self._drain_firsts(block=False)
        consumed = 0  # tokens actually fed to requests (perf ledger)
        if res.ndim == 3:
            # Speculative call [K, S, T+1]: per row, columns :T are the
            # sampled tokens and column T is n_out; the first n_out
            # tokens are real (accepted drafts + the residual sample).
            # Positions advance one per token, same as plain decode.
            for k in range(res.shape[0]):
                for s, req in snapshot:
                    if req.finished or self._running.get(s) is not req:
                        continue
                    n = int(res[k, s, -1])
                    if n:
                        self._m_spec.observe(n)
                        self._spec_ema = (0.9 * self._spec_ema
                                          + 0.1 * n)
                        # Accept/reject accounting: each verify block
                        # offered spec_draft drafts and accepted n-1.
                        req.spec_accepted += n - 1
                        req.spec_drafted += self.spec_draft
                    for i in range(n):
                        if req.finished \
                                or self._running.get(s) is not req:
                            break
                        self._positions[s] += 1
                        consumed += 1
                        self._consume_token(req, int(res[k, s, i]))
        else:
            for k in range(res.shape[0]):
                for s, req in snapshot:
                    if req.finished or self._running.get(s) is not req:
                        # Request ended earlier in this call, or the
                        # slot was re-admitted to a newer request: drop
                        # the token.
                        continue
                    self._positions[s] += 1
                    consumed += 1
                    self._consume_token(req, int(res[k, s]))
        for _, req in snapshot:
            self._flush_emit(req)
        if self._tracer.enabled:
            # One step record per retired call (process-level row) and
            # one decode_step span per participating request: batch
            # occupancy and slot utilization AT DISPATCH TIME, which is
            # what the device actually computed over. The perf ledger's
            # extras: token rows the fixed shapes computed (all S slots
            # every step; spec calls verify T = draft+1 positions per
            # step), tokens actually consumed, the call's KV bucket and
            # the FLOP estimate both imply.
            t1 = time.monotonic()
            spec = res.ndim == 3
            constrained = sum(1 for _, r in snapshot
                              if r.fsm is not None)
            occupancy = round(len(snapshot) / max(1, self.num_slots), 3)
            rows = int(res.shape[0]) * self.num_slots \
                * (res.shape[2] - 1 if spec else 1)
            # kv_bytes: what this call's attention streamed from HBM —
            # every step reads kv_len rows for all S slots, at the
            # cache's HONEST element size (int8 rows + scales under
            # KV_QUANT=int8, not an assumed bf16). Feeds the ledger's
            # KV-bandwidth-utilisation figure next to MFU.
            self._tracer.step(
                "engine_step", t_disp, t1, steps=int(res.shape[0]),
                batch=len(snapshot), slots=self.num_slots,
                occupancy=occupancy, kind="spec" if spec else "plain",
                program=program,
                tokens=consumed, rows=rows, kv_len=kv_len,
                flops=self._perf.call_flops(consumed, kv_len),
                kv_bytes=int(res.shape[0]) * self._kv_read_rows(
                    snapshot, kv_len) * self._kv_row_bytes,
                # weight_bytes: the weights streamed once per step at
                # their RESIDENT size (bf16 / int8+scales / packed
                # int4+scales) — /perf's bandwidth and FLOP/byte read
                # this instead of assuming a bf16 footprint.
                weight_bytes=(int(res.shape[0])
                              * self._weight_bytes_per_step),
                # Mask-apply attribution (docs/STRUCTURED.md): rows
                # with constrained>0 ran the fsm decode variant — the
                # per-step mask gather/unpack cost is the step-duration
                # delta against constrained-free rows of the same
                # (steps, kv_len) shape in the perf ledger.
                **({"constrained": constrained} if constrained else {}))
            for s, req in snapshot:
                self._tracer.add_span(
                    req.request_id, "decode_step", t_disp, t1,
                    slot=s, batch=len(snapshot), occupancy=occupancy,
                    tokens=req.generated - gen_before.get(id(req), 0),
                    kind="spec" if spec else "plain")

    def _consume_token(self, req: _Request, token_id: int) -> None:
        """Handle one newly sampled token for a request (host side)."""
        if req.cancelled:
            self._finish(req, "cancelled")
            return
        if token_id in self.tokenizer.eos_ids \
                and not req.params.ignore_eos:
            self._finish(req, "stop")
            return
        slot = req.slot
        assert slot is not None and req.detok is not None
        slot.tokens.append(token_id)
        req.generated += 1
        if req.fsm is not None:
            # Host mirror of the on-device FSM advance: one dict-free
            # table lookup per token. The device copy is authoritative
            # inside the scan; this replay is what _finish, the
            # terminal-accept check and jump-forward read.
            req.fsm_state = req.fsm.step(req.fsm_state, token_id)
        now = time.monotonic()
        if req.last_token_at is not None:
            gap_ms = (now - req.last_token_at) * 1000
            self._m_intertok.observe(gap_ms)
            if gap_ms > req.max_gap_ms:
                req.max_gap_ms = gap_ms  # SLO inter-token SLI
        req.last_token_at = now
        if req.first_token_at is None:
            req.first_token_at = now
            self._m_ttft.observe(
                (req.first_token_at - req.submitted_at) * 1000)
            self._tracer.event(req.request_id, "first_token")
        self._m_tokens.inc()
        t_detok = time.monotonic()
        delta = req.detok.push(token_id)
        req.detok_s += time.monotonic() - t_detok
        if delta:
            self._stream_text(req, delta)
        if req.finished:
            return  # stop string hit inside _stream_text
        if req.fsm is not None and req.fsm.is_terminal(req.fsm_state):
            # The FSM reached an accept state with EOS as the only
            # continuation: the document is complete. Finish with
            # "stop" NOW — before the budget check below, so a
            # generation that completes its document on its last
            # budgeted token reports "stop", not "length" — and
            # without spending a decode step on the EOS itself.
            self._finish(req, "stop")
            return
        if req.generated >= req.params.max_tokens:
            self._finish(req, "length")
        elif len(slot.tokens) >= self.usable_len:
            self._finish(req, "length")
        elif req.fsm is not None:
            self._st_note_jump_candidate(req)

    def _stream_text(self, req: _Request, delta: str) -> None:
        """Emit text, holding back any suffix that could start a stop seq."""
        stops = req.params.stop
        req.pending_text += delta
        if not stops:
            req.emit_buf += req.pending_text
            req.pending_text = ""
            return
        for stop in stops:
            idx = req.pending_text.find(stop)
            if idx >= 0:
                req.emit_buf += req.pending_text[:idx]
                req.pending_text = ""
                self._finish(req, "stop", suppress_flush=True)
                return
        hold = 0
        for stop in stops:
            for k in range(min(len(stop) - 1, len(req.pending_text)), 0, -1):
                if req.pending_text.endswith(stop[:k]):
                    hold = max(hold, k)
                    break
        cut = len(req.pending_text) - hold
        emit_now, req.pending_text = req.pending_text[:cut], req.pending_text[cut:]
        if emit_now:
            req.emit_buf += emit_now

    def _finish(self, req: _Request, reason: str, error: str | None = None,
                suppress_flush: bool = False, code: str = "model_error",
                retry_after: float | None = None) -> None:
        # Atomic check-and-set against the watchdog thread's
        # force_fail: without the lock, a request completing at the
        # instant its stall crosses the cancel threshold could get BOTH
        # a success terminal and a "stalled" error, and an ok=False SLO
        # sample for a request that actually finished.
        with self._term_lock:
            if req.finished:
                return
            req.finished = True
        if req.admitted_at is not None:
            # Admission→finish wall time feeds the scheduler's
            # service-time EMA (wait estimates, retry_after hints).
            self._sched.note_service_time(
                time.monotonic() - req.admitted_at)
        if reason != "cancelled":
            # Cancels are the client's choice, not an SLO sample;
            # watchdog-failed requests were already recorded as errors
            # by force_fail (idempotent either way). Queue-deadline
            # expiry and KV block-pool exhaustion are load SHEDDING
            # (utils/errors.ENGINE_SHED_CODES, the same taxonomy the
            # serving layers map to 429/retry_after): counting them as
            # SLO errors would page the error-rate objective for
            # exactly the mechanisms that protect the admitted
            # requests' latency (docs/OBSERVABILITY.md).
            if code in ENGINE_SHED_CODES and error is not None:
                with self._term_lock:
                    already = req.slo_recorded
                    req.slo_recorded = True
                if not already:
                    self._slo.record_shed(req.params.priority)
            else:
                self._record_slo(req, ok=error is None)
        if req.fsm is not None:
            self._st_release(req)
        slot = req.slot
        if slot is not None:
            decoding = self._running.get(slot.index) is req
            slot.active = False
            slot.last_used = time.monotonic()
            self._running.pop(slot.index, None)
            self._active_mask[slot.index] = False
            self._temps[slot.index] = 0.0
            self._reps[slot.index] = 1.0
            self._press[slot.index] = 0.0
            self._freqs[slot.index] = 0.0
            if decoding:
                # KV rows are written only up to the position reached by
                # *feeding* tokens; a final token kept on max_tokens/stop
                # was sampled but never fed — not trusted for reuse.
                # (If the request died before activation, the prefill
                # paths maintained kv_written themselves and the
                # positions mirror is stale — leave it alone.)
                slot.kv_written = min(slot.length,
                                      int(self._positions[slot.index]))
            # Host positions mirror is authoritative again (the device
            # copy may have speculatively advanced past the kept length).
            self._positions[slot.index] = slot.length
            self._dirty_slots.add(slot.index)
            if self.paged and slot.session_id is not None:
                # Reclaim decode-growth slack past the trusted rows.
                # Safe against the still-draining pipeline: its
                # garbage writes land in the freed blocks strictly
                # before any reallocation's writes (in-order dispatch
                # stream, old table captured at dispatch).
                self._kv_blocks.truncate(slot.index, slot.kv_written)
                # Donate the finished turn's clean prefix to the radix
                # tree NOW (kv_written just settled): the next request
                # — any session sharing this prefix, not just this one
                # — inherits the blocks with zero registration. Runs
                # before the deferred release below so the holds land
                # while the table refs still pin the blocks.
                self._radix_insert_slot(slot)
            sid = slot.session_id
            if sid is not None and sid in self._release_after:
                self._release_after.discard(sid)
                self.slots.release_session(sid)
                self._kv_pool.purge(sid)  # deferred release: same rule
        self._by_id.pop(req.request_id, None)

        if not suppress_flush and req.detok is not None \
                and reason not in ("cancelled",):
            req.pending_text += req.detok.flush()
        if req.pending_text and reason != "cancelled":
            # Final flush still honours stop strings (text that was held
            # back may contain one).
            text = req.pending_text
            for stop in req.params.stop:
                idx = text.find(stop)
                if idx >= 0:
                    text = text[:idx]
                    reason = "stop"
            req.emit_buf += text
        req.pending_text = ""
        self._flush_emit(req)

        if self._tracer.enabled:
            now = time.monotonic()
            if req.admitted_at is None:
                # Never admitted (cancelled/errored in the queue): the
                # whole lifetime was queue wait.
                self._tracer.add_span(req.request_id, "queue_wait",
                                      req.submitted_at, now,
                                      summary=True)
            if req.decode_started_at is not None:
                attrs: dict[str, Any] = {
                    "tokens": req.generated, "finish_reason": reason,
                    "prompt_tokens": len(req.prompt_tokens)}
                if req.spec_drafted:
                    attrs["spec_accepted"] = req.spec_accepted
                    attrs["spec_rejected"] = (req.spec_drafted
                                              - req.spec_accepted)
                if req.fsm is not None:
                    attrs["structured"] = True
                    attrs["jump_tokens"] = req.jump_tokens
                self._tracer.add_span(req.request_id, "decode",
                                      req.decode_started_at, now,
                                      summary=True, **attrs)
            if req.detok_s > 0:
                # Aggregate span: total detokenize time, anchored so it
                # ends at finish (per-token spans would be absurdly
                # fine-grained — this keeps the phase visible in the
                # report and the timeline without per-token overhead).
                self._tracer.add_span(req.request_id, "detokenize",
                                      now - req.detok_s, now,
                                      summary=True, aggregate=True)
            self._tracer.set_phase(req.request_id, "finishing")

        if error is not None:
            event = {"type": "error", "error": error, "code": code}
            if retry_after is not None:
                event["retry_after"] = retry_after
            self._emit(req, event)
            return
        duration = time.monotonic() - req.submitted_at
        ttft_ms = ((req.first_token_at or time.monotonic())
                   - req.submitted_at) * 1000
        self._emit(req, {
            "type": "cancelled" if reason == "cancelled" else "done",
            "finish_reason": reason,
            "stats": {
                "tokens_generated": req.generated,
                "processing_time_ms": duration * 1000,
                "tokens_per_second": req.generated / duration
                if duration > 0 else 0.0,
                "ttft_ms": ttft_ms,
                "prompt_tokens": len(req.prompt_tokens),
                # Tokens actually PREFILLED (the delta after resident/
                # restore reuse) — the honest prefill-throughput feed
                # for the fleet's migration policy; prompt_tokens over
                # TTFT would overstate throughput by the cache-hit
                # fraction.
                "prefill_tokens": req.prefill_tokens,
            },
        })

    def _flush_emit(self, req: _Request) -> None:
        """Send the text batched during one retirement as a single token
        event. At full batch this collapses steps_per_call × num_slots
        queue crossings per call into one per request — the host-side
        per-token cost (call_soon_threadsafe + event-loop wakeup) was a
        measurable slice of aggregate throughput."""
        if req.emit_buf:
            text, req.emit_buf = req.emit_buf, ""
            event: dict = {"type": "token", "text": text}
            if req.params.journey:
                # Journey stamps (observability/journey.py): the
                # retirement's fetch-wait start / fetch-landed marks
                # plus the enqueue instant. The serving loop adds its
                # dequeue and ws-write boundaries; out-of-order stamps
                # (a flush from a different retirement than the fetch
                # the marks describe) are clamped forward there.
                event["j"] = {"w": self._j_wait0,
                              "f": self._j_fetched,
                              "e": time.monotonic()}
            self._emit(req, event)

    def _emit(self, req: _Request, event: dict) -> None:
        try:
            req.loop.call_soon_threadsafe(req.out_queue.put_nowait, event)
        except RuntimeError:
            pass  # client loop already closed; drop
