"""Legacy remote backends behind the engine seam: vLLM and Ollama.

Back-compat parity with the reference's L1 handler layer — the vLLM
OpenAI-SSE client (app/core/vllm_handler.py:117-308) and the Ollama
NDJSON client (app/core/ollama_handler.py:110-339) — rebuilt as
EngineBase implementations so the serving layer is provider-pluggable
(tpu | vllm | ollama) exactly as SURVEY.md §7 prescribes. Fully async
(aiohttp): no sync-generator-in-async-loop stalls (reference flaw,
SURVEY.md §3.3), and cancellation closes the HTTP stream immediately.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, AsyncGenerator

import aiohttp

from fasttalk_tpu.engine.engine import (EngineBase, GenerationParams,
                                        raw_prompt_text)
from fasttalk_tpu.observability.trace import (current_traceparent,
                                              get_tracer)
from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.utils.errors import (AdmissionRejected, ErrorCategory,
                                       LLMServiceError)
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("engine.remote")


class _RemoteEngine(EngineBase):
    """Shared plumbing: lazy client session, cancel flags, lifecycle,
    and bounded upstream concurrency — at most ``max_inflight``
    requests stream from the backend at once, so the backpressure and
    shedding discipline of the TPU branch (docs/SCHEDULING.md) applies
    uniformly here: a waiter that cannot start within
    ``admission_timeout_s`` is shed with AdmissionRejected +
    retry_after instead of piling onto a saturated upstream."""

    def __init__(self, base_url: str, timeout_s: float = 600.0,
                 max_inflight: int = 32,
                 admission_timeout_s: float = 30.0,
                 connect_retries: int = 2):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.max_inflight = max(1, max_inflight)
        self.admission_timeout_s = admission_timeout_s
        # Bounded retries for idempotent upstream failures: a connect
        # error or 5xx BEFORE the first streamed chunk left nothing
        # client-visible, so retrying is safe; after the first chunk a
        # failure surfaces (the fleet router owns mid-stream recovery).
        self.connect_retries = max(0, connect_retries)
        self._sem = asyncio.Semaphore(self.max_inflight)
        self._inflight = 0
        self._draining = False
        self._cancelled: set[str] = set()
        self._session: aiohttp.ClientSession | None = None
        self._started = False
        self._tracer = get_tracer()
        m = get_metrics()
        self._m_shed = m.counter(
            "remote_shed_total",
            "remote-backend submissions shed (upstream saturated past "
            "the admission timeout, or draining)")
        self._m_inflight = m.gauge(
            "remote_inflight_requests",
            "requests currently streaming from the remote backend")
        self._m_retries = m.counter(
            "remote_connect_retries_total",
            "pre-first-token upstream failures retried (connect "
            "errors and 5xx before any output)")

    def _connect_retry_delay(self, attempt: int, chunks: int,
                             ) -> float | None:
        """Backoff before retrying a pre-first-token upstream failure,
        or None when the failure must surface: something was already
        streamed (the retry is no longer idempotent) or the bounded
        attempts are exhausted. Jittered exponential, capped at 2 s —
        the same shape as the serving layer's RetryManager, scaled for
        a client inside a live request."""
        import random

        if chunks > 0 or attempt >= self.connect_retries:
            return None
        base = min(2.0, 0.25 * (2 ** attempt))
        return base * (1.0 + random.uniform(-0.25, 0.25))

    def _upstream_retry_delay(self, e: BaseException, attempt: int,
                              chunks: int, request_id: str,
                              name: str) -> float:
        """Classify one streaming failure: return the backoff delay
        when it is a retryable pre-first-token failure (connect error
        or upstream 5xx with nothing streamed), or raise what must
        surface — a 4xx is the request's fault and will 4xx again, and
        anything after the first chunk is no longer idempotent. Shared
        by every provider client so the idempotency rule cannot drift
        between them."""
        is_5xx = (isinstance(e, LLMServiceError)
                  and e.details.get("status", 0) >= 500)
        if isinstance(e, LLMServiceError) and not is_5xx:
            raise e
        delay = self._connect_retry_delay(attempt, chunks)
        if delay is None:
            if isinstance(e, LLMServiceError):
                e.retry_after = e.retry_after or 2.0
                raise e
            raise LLMServiceError(
                f"{name} connection failed: {e}",
                category=ErrorCategory.CONNECTION,
                retry_after=2.0) from e
        self._m_retries.inc()
        log.warning(f"[{request_id}] upstream failed pre-first-token "
                    f"({e}); retry {attempt + 1}/"
                    f"{self.connect_retries} in {delay:.2f}s")
        return delay

    async def _acquire_upstream(self) -> None:
        """Take an upstream slot or shed. Raises AdmissionRejected when
        draining or when ``max_inflight`` streams are already running
        and none frees up within the admission timeout."""
        if self._draining:
            self._m_shed.inc()
            raise AdmissionRejected(
                "server is draining: finishing in-flight requests, not "
                "accepting new ones", retry_after=5.0, reason="draining")
        try:
            await asyncio.wait_for(self._sem.acquire(),
                                   timeout=self.admission_timeout_s)
        except asyncio.TimeoutError:
            self._m_shed.inc()
            raise AdmissionRejected(
                f"upstream at capacity ({self.max_inflight} requests in "
                f"flight for {self.admission_timeout_s:.0f}s)",
                retry_after=min(30.0, max(1.0,
                                          self.admission_timeout_s / 4)),
                reason="upstream_saturated") from None
        self._inflight += 1
        self._m_inflight.set(self._inflight)

    def _release_upstream(self) -> None:
        self._inflight -= 1
        self._m_inflight.set(self._inflight)
        self._sem.release()

    def begin_drain(self) -> None:
        self._draining = True

    def pending_requests(self) -> int:
        return self._inflight

    def start(self) -> None:
        self._started = True

    def shutdown(self) -> None:
        self._started = False
        session, self._session = self._session, None
        if session is not None and not session.closed:
            try:
                loop = asyncio.get_event_loop()
                if loop.is_running():
                    loop.create_task(session.close())
                else:
                    loop.run_until_complete(session.close())
            except RuntimeError:
                pass

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s,
                                              sock_connect=10))
        return self._session

    def cancel(self, request_id: str) -> bool:
        self._cancelled.add(request_id)
        return True

    def release_session(self, session_id: str) -> None:
        pass  # remote backends hold no per-session device state

    def get_stats(self) -> dict:
        return {"backend": self.base_url,
                "cancelled_pending": len(self._cancelled),
                "inflight": self._inflight,
                "max_inflight": self.max_inflight,
                "draining": self._draining}

    def _sync_get(self, url: str, timeout: float = 3.0) -> Any:
        import requests

        r = requests.get(url, timeout=timeout)
        r.raise_for_status()
        return r

    def _trace_start(self, request_id: str, session_id: str,
                     backend: str) -> bool:
        """Register the request with the span tracer (phase: upstream).
        Returns whether this engine owns the trace's finish (False when
        the serving layer started it first)."""
        tracer = self._tracer
        owned = tracer.start(request_id, session_id)
        tracer.set_phase(request_id, "upstream", backend=backend)
        return owned

    def set_trace_component(self, component: str) -> None:
        """Tag this engine's spans with a fleet component name (see
        EngineBase.set_trace_component)."""
        self._tracer = get_tracer().scoped(component)

    def _trace_end(self, request_id: str, owned: bool, t0: float,
                   ttft_ms: float | None, chunks: int,
                   backend: str) -> None:
        """Close the upstream_stream span (covers connect + the whole
        body read — a remote engine has no queue/prefill visibility, so
        this is the request's single engine-side phase)."""
        tracer = self._tracer
        tracer.add_span(request_id, "upstream_stream", t0,
                        time.monotonic(), summary=True, backend=backend,
                        chunks=chunks,
                        **({"ttft_ms": round(ttft_ms, 3)}
                           if ttft_ms is not None else {}))
        if owned:
            tracer.finish(request_id)

    def _finish_stats(self, chunks: int, started: float,
                      ttft: float | None,
                      prompt_tokens: int | None = None,
                      completion_tokens: int | None = None) -> dict:
        """Terminal stats for a remote stream.

        A stream CHUNK is not a token (the reference conflated the two —
        SURVEY.md §5 metrics gap, explicitly on the don't-copy list), so
        ``tokens_generated``/``tokens_per_second`` are reported only when
        the backend supplied its own authoritative token counts (vLLM
        usage via stream_options, Ollama eval_count); otherwise they are
        None and ``chunks_generated`` carries the honestly-labelled
        chunk count."""
        dur = time.monotonic() - started
        return {
            "chunks_generated": chunks,
            "tokens_generated": completion_tokens,
            "processing_time_ms": dur * 1000,
            "tokens_per_second": (completion_tokens / dur
                                  if completion_tokens is not None
                                  and dur > 0 else None),
            "ttft_ms": ttft,
            "prompt_tokens": prompt_tokens,
        }


class VLLMRemoteEngine(_RemoteEngine):
    """OpenAI-compatible SSE streaming client against an external vLLM
    (reference: vllm_handler.py — base URL config at config.py:96)."""

    def __init__(self, base_url: str, model: str,
                 api_key: str = "not-needed", timeout_s: float = 600.0,
                 max_inflight: int = 32,
                 admission_timeout_s: float = 30.0,
                 connect_retries: int = 2):
        super().__init__(base_url, timeout_s, max_inflight=max_inflight,
                         admission_timeout_s=admission_timeout_s,
                         connect_retries=connect_retries)
        self.model = model
        self.api_key = api_key
        # Set after a backend 400s on stream_options (pre-0.4.3 vLLM,
        # strict OpenAI-compatible proxies): dropped for the engine's
        # lifetime; stats then fall back to chunk counting.
        self._no_stream_options = False
        # Same lifecycle for repetition_penalty: vLLM accepts it as a
        # sampling extension, but strict OpenAI-compatible backends 400
        # on the unknown param — drop it (not the request) and retry.
        self._no_repetition_penalty = False

    async def generate(self, request_id: str, session_id: str,
                       messages: list[dict], params: GenerationParams,
                       ) -> AsyncGenerator[dict, None]:
        client = await self._client()
        body = {
            "model": self.model,
            "temperature": params.temperature,
            "top_p": params.top_p,
            "max_tokens": params.max_tokens,
            "stream": True,
            # OpenAI-style penalties pass straight through.
            "presence_penalty": params.presence_penalty,
            "frequency_penalty": params.frequency_penalty,
        }
        if params.repeat_penalty != 1.0 and not self._no_repetition_penalty:
            body["repetition_penalty"] = params.repeat_penalty
        if params.structured is not None:
            # Structured passthrough (docs/STRUCTURED.md): the JSON
            # kinds map onto the upstream's own response_format; kinds
            # the OpenAI wire protocol cannot express fail loudly —
            # silently serving unconstrained output would break the
            # validity contract the client asked for.
            kind = params.structured.get("kind")
            if kind == "json_object":
                body["response_format"] = {"type": "json_object"}
            elif kind == "json_schema":
                body["response_format"] = {
                    "type": "json_schema",
                    "json_schema": {"name": "response", "strict": True,
                                    "schema":
                                        params.structured["schema"]}}
            else:
                raise LLMServiceError(
                    f"structured kind {kind!r} cannot pass through an "
                    "OpenAI-compatible upstream (json_object/"
                    "json_schema only)",
                    category=ErrorCategory.VALIDATION,
                    recoverable=False)
        if not self._no_stream_options:
            # Ask the backend for its own token accounting (an OpenAI /
            # vLLM-supported option): the final chunk then carries
            # usage.completion_tokens, the only true token count a
            # remote client can get (chunk != token, SURVEY.md §5).
            body["stream_options"] = {"include_usage": True}
        if params.raw_prompt:
            # /v1/completions passthrough: raw prompt, upstream's own
            # legacy endpoint (no chat template anywhere).
            url = f"{self.base_url}/completions"
            body["prompt"] = raw_prompt_text(messages)
        else:
            url = f"{self.base_url}/chat/completions"
            body["messages"] = messages
        if params.stop:
            body["stop"] = params.stop
        started = time.monotonic()
        ttft = None
        chunks = 0
        prompt_toks: int | None = None
        completion_toks: int | None = None
        finish = "stop"
        await self._acquire_upstream()
        trace_owned = self._trace_start(request_id, session_id, "vllm")
        retry_attempt = 0
        try:
            while True:  # pre-first-token connect/5xx retry loop
                try:
                    if _fp.enabled:
                        # Chaos seam: raised as the transport error
                        # type so the pre-first-token retry (and the
                        # router's replica-fault classifier) treat it
                        # exactly like a real connect failure.
                        await _fp.fire_async("remote.connect",
                                 exc=aiohttp.ClientConnectionError,
                                 request_id=request_id)
                    # Trace-context propagation (docs/OBSERVABILITY.md
                    # "Fleet tracing"): carry the fleet trace id on the
                    # dispatch so a remote replica's serving edge joins
                    # its spans to the router's trace instead of
                    # minting a disjoint one.
                    headers = {"Authorization": f"Bearer {self.api_key}"}
                    tp = current_traceparent()
                    if tp is not None:
                        headers["traceparent"] = tp
                    for _attempt in range(3):
                        async with client.post(
                                url, json=body, headers=headers,
                                ) as resp:
                            if resp.status != 200:
                                text = await resp.text()
                                if resp.status == 400 \
                                        and "stream_options" in body \
                                        and "stream_options" in text:
                                    # The backend names stream_options
                                    # in its 400 (pre-0.4.3 vLLM,
                                    # strict proxies): drop the
                                    # parameter for this engine's
                                    # lifetime and retry once (stats
                                    # degrade to honest chunk counts).
                                    # Any OTHER 400 — context overflow,
                                    # bad params — surfaces unretried
                                    # below.
                                    self._no_stream_options = True
                                    del body["stream_options"]
                                    continue
                                if resp.status == 400 \
                                        and "repetition_penalty" in body \
                                        and "repetition_penalty" in text:
                                    # Strict OpenAI-compatible backend
                                    # without the vLLM sampling
                                    # extension: serve without the
                                    # penalty rather than failing every
                                    # generation.
                                    self._no_repetition_penalty = True
                                    del body["repetition_penalty"]
                                    continue
                                err = LLMServiceError(
                                    f"vLLM backend error {resp.status}: "
                                    f"{text[:200]}",
                                    category=ErrorCategory.CONNECTION,
                                    details={"status": resp.status})
                                raise err
                            async for raw in resp.content:
                                if _fp.enabled:
                                    # Mid-stream failure: chunks > 0
                                    # makes the retry non-idempotent,
                                    # so this must surface terminally.
                                    await _fp.fire_async("remote.stream",
                                             exc=aiohttp.ClientError,
                                             request_id=request_id)
                                if request_id in self._cancelled:
                                    self._cancelled.discard(request_id)
                                    yield {"type": "cancelled",
                                           "finish_reason": "cancelled",
                                           "stats": self._finish_stats(
                                               chunks, started, ttft,
                                               prompt_toks,
                                               completion_toks)}
                                    return
                                line = raw.decode("utf-8",
                                                  "replace").strip()
                                if not line.startswith("data:"):
                                    continue
                                payload = line[5:].strip()
                                if payload == "[DONE]":
                                    break
                                try:
                                    obj = json.loads(payload)
                                except json.JSONDecodeError:
                                    continue
                                usage = obj.get("usage")
                                if usage:
                                    # include_usage final chunk (empty
                                    # choices): backend-authoritative
                                    # token counts.
                                    prompt_toks = usage.get(
                                        "prompt_tokens", prompt_toks)
                                    completion_toks = usage.get(
                                        "completion_tokens",
                                        completion_toks)
                                choices = obj.get("choices") or []
                                if not choices:
                                    continue
                                fr = choices[0].get("finish_reason")
                                if fr:
                                    finish = fr
                                # chat streams deltas; completions
                                # streams text
                                content = (choices[0].get("text")
                                           if params.raw_prompt
                                           else choices[0].get("delta",
                                                               {})
                                           .get("content"))
                                if content:
                                    chunks += 1
                                    if ttft is None:
                                        ttft = (time.monotonic()
                                                - started) * 1000
                                        self._tracer.event(request_id,
                                                           "first_chunk")
                                    yield {"type": "token",
                                           "text": content}
                        break  # stream consumed; no param retry
                    break  # success: leave the connect-retry loop
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        LLMServiceError) as e:
                    delay = self._upstream_retry_delay(
                        e, retry_attempt, chunks, request_id, "vLLM")
                    retry_attempt += 1
                    await asyncio.sleep(delay)
            yield {"type": "done", "finish_reason": finish,
                   "stats": self._finish_stats(chunks, started, ttft,
                                               prompt_toks,
                                               completion_toks)}
        finally:
            self._release_upstream()
            self._trace_end(request_id, trace_owned, started, ttft,
                            chunks, "vllm")
            self._cancelled.discard(request_id)

    def check_connection(self) -> bool:
        if not self._started:
            return False
        try:
            root = self.base_url.rsplit("/v1", 1)[0]
            self._sync_get(f"{root}/health")
            return True
        except Exception:
            return False

    def get_model_info(self) -> dict:
        # Static (no network): this runs inside async handlers, where a
        # blocking round-trip would stall the event loop.
        return {"model": self.model, "backend": "vllm",
                "base_url": self.base_url}

    def list_available_models(self) -> list[str]:
        """Network call — do not use from the event loop."""
        try:
            r = self._sync_get(f"{self.base_url}/models")
            return [m.get("id") for m in r.json().get("data", [])]
        except Exception:
            return []


class OllamaRemoteEngine(_RemoteEngine):
    """NDJSON streaming client against an external Ollama
    (reference: ollama_handler.py — base URL config at config.py:116)."""

    def __init__(self, base_url: str, model: str,
                 keep_alive: str = "5m", timeout_s: float = 600.0,
                 max_inflight: int = 32,
                 admission_timeout_s: float = 30.0,
                 connect_retries: int = 2):
        super().__init__(base_url, timeout_s, max_inflight=max_inflight,
                         admission_timeout_s=admission_timeout_s,
                         connect_retries=connect_retries)
        self.model = model
        self.keep_alive = keep_alive

    async def generate(self, request_id: str, session_id: str,
                       messages: list[dict], params: GenerationParams,
                       ) -> AsyncGenerator[dict, None]:
        client = await self._client()
        body = {
            "model": self.model,
            "stream": True,
            "keep_alive": self.keep_alive,
            "options": {
                "temperature": params.temperature,
                "top_p": params.top_p,
                "top_k": params.top_k,
                "num_predict": params.max_tokens,
                # Explicit where the reference's gateway relied on the
                # engine default (~1.1): the applied penalty is now in
                # the request record, not implicit engine state.
                "repeat_penalty": params.repeat_penalty,
                "presence_penalty": params.presence_penalty,
                "frequency_penalty": params.frequency_penalty,
            },
        }
        if params.raw_prompt:
            # /api/generate with raw=true: Ollama's untemplated path.
            url = f"{self.base_url}/api/generate"
            body["prompt"] = raw_prompt_text(messages)
            body["raw"] = True
        else:
            url = f"{self.base_url}/api/chat"
            body["messages"] = messages
        if params.stop:
            body["options"]["stop"] = params.stop
        if params.structured is not None:
            # Ollama's structured-outputs surface: format="json" for
            # the generic contract, format=<schema> for a JSON Schema.
            # Other kinds cannot be expressed — fail loudly rather
            # than silently dropping the constraint.
            kind = params.structured.get("kind")
            if kind == "json_object":
                body["format"] = "json"
            elif kind == "json_schema":
                body["format"] = params.structured["schema"]
            else:
                raise LLMServiceError(
                    f"structured kind {kind!r} cannot pass through an "
                    "Ollama upstream (json_object/json_schema only)",
                    category=ErrorCategory.VALIDATION,
                    recoverable=False)
        started = time.monotonic()
        ttft = None
        chunks = 0
        prompt_toks: int | None = None
        completion_toks: int | None = None
        await self._acquire_upstream()
        trace_owned = self._trace_start(request_id, session_id, "ollama")
        retry_attempt = 0
        try:
            while True:  # pre-first-token connect/5xx retry loop
                try:
                    if _fp.enabled:
                        await _fp.fire_async("remote.connect",
                                 exc=aiohttp.ClientConnectionError,
                                 request_id=request_id)
                    tp = current_traceparent()
                    async with client.post(
                            url, json=body,
                            headers={"traceparent": tp} if tp else None,
                            ) as resp:
                        if resp.status != 200:
                            text = await resp.text()
                            raise LLMServiceError(
                                f"Ollama backend error {resp.status}: "
                                f"{text[:200]}",
                                category=ErrorCategory.CONNECTION,
                                details={"status": resp.status})
                        async for raw in resp.content:
                            if _fp.enabled:
                                await _fp.fire_async("remote.stream",
                                         exc=aiohttp.ClientError,
                                         request_id=request_id)
                            if request_id in self._cancelled:
                                self._cancelled.discard(request_id)
                                yield {"type": "cancelled",
                                       "finish_reason": "cancelled",
                                       "stats": self._finish_stats(
                                           chunks, started, ttft,
                                           prompt_toks, completion_toks)}
                                return
                            line = raw.decode("utf-8", "replace").strip()
                            if not line:
                                continue
                            try:
                                obj = json.loads(line)
                            except json.JSONDecodeError:
                                continue
                            # /api/chat nests under message;
                            # /api/generate is flat
                            content = (obj.get("response")
                                       if params.raw_prompt
                                       else (obj.get("message") or {})
                                       .get("content"))
                            if content:
                                chunks += 1
                                if ttft is None:
                                    ttft = (time.monotonic()
                                            - started) * 1000
                                    self._tracer.event(request_id,
                                                       "first_chunk")
                                yield {"type": "token", "text": content}
                            if obj.get("done"):
                                # Final NDJSON object carries Ollama's
                                # own token accounting (the reference
                                # threw these away and counted chunks,
                                # ollama_handler.py:233-339).
                                prompt_toks = obj.get(
                                    "prompt_eval_count", prompt_toks)
                                completion_toks = obj.get(
                                    "eval_count", completion_toks)
                                break
                    break  # success: leave the connect-retry loop
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        LLMServiceError) as e:
                    delay = self._upstream_retry_delay(
                        e, retry_attempt, chunks, request_id, "Ollama")
                    retry_attempt += 1
                    await asyncio.sleep(delay)
            yield {"type": "done", "finish_reason": "stop",
                   "stats": self._finish_stats(chunks, started, ttft,
                                               prompt_toks,
                                               completion_toks)}
        finally:
            self._release_upstream()
            self._trace_end(request_id, trace_owned, started, ttft,
                            chunks, "ollama")
            self._cancelled.discard(request_id)

    def check_connection(self) -> bool:
        if not self._started:
            return False
        try:
            self._sync_get(f"{self.base_url}/")
            return True
        except Exception:
            return False

    def get_model_info(self) -> dict:
        # Static (no network): see VLLMRemoteEngine.get_model_info.
        return {"model": self.model, "backend": "ollama",
                "base_url": self.base_url}

    def list_available_models(self) -> list[str]:
        """Network call — do not use from the event loop."""
        try:
            r = self._sync_get(f"{self.base_url}/api/tags")
            return [m.get("name") for m in r.json().get("models", [])]
        except Exception:
            return []
