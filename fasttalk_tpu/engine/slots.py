"""Decode-slot management: session pinning, prefix reuse, LRU eviction.

The continuous-batching engine decodes a fixed batch of S slots (static
shapes for XLA). Each slot owns one contiguous region of the KV cache
arrays. A *session* (WebSocket conversation) is pinned to a slot between
turns, so its KV stays resident in TPU HBM and a follow-up turn only
prefills the new tokens — the north-star requirement the reference could
not meet (its KV lived inside an external engine container and was gone
between HTTP calls; BASELINE.json north_star).

All methods are called from the engine thread only — no locks by design
(contrast: the reference's lock-discipline bugs, SURVEY.md §5 race
detection: get_detailed_stats self-deadlock).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


def _lcp(a: list[int], b: list[int], limit: int) -> int:
    """Length of the longest common prefix of a[:limit] and b[:limit].
    Slice-equality blocks keep the comparison at C speed — a per-token
    Python loop over multi-thousand-token resident histories runs on
    the engine thread inside admission and costs TTFT."""
    n = 0
    step = 256
    while n < limit:
        m = min(step, limit - n)
        if a[n:n + m] == b[n:n + m]:
            n += m
            continue
        for i in range(n, n + m):
            if a[i] != b[i]:
                return i
        return n + m
    return n


@dataclass
class Slot:
    index: int
    session_id: str | None = None     # pinned session (None = free)
    tokens: list[int] = field(default_factory=list)  # kept token ids
    # How many leading entries of ``tokens`` have their KV actually
    # written in HBM. A token's KV is written when it is *fed*, one step
    # after it is sampled — so a request finishing on max_tokens keeps a
    # final token whose KV row was never written. Prefix reuse must not
    # trust rows beyond this watermark.
    kv_written: int = 0
    active: bool = False              # currently decoding a request
    last_used: float = 0.0

    @property
    def length(self) -> int:
        return len(self.tokens)


class SlotManager:
    def __init__(self, num_slots: int, max_len: int, on_evict=None,
                 on_unpin=None):
        self.slots = [Slot(i) for i in range(num_slots)]
        self.max_len = max_len
        self._by_session: dict[str, Slot] = {}
        # Called with the victim Slot BEFORE an LRU eviction clears it
        # (engine hook: snapshot the resident KV to the host pool,
        # kvcache/offload.py). Only acquire()-driven evictions fire it
        # — an explicit release_session means the session is done and
        # its KV is not worth keeping anywhere.
        self.on_evict = on_evict
        # Called with the Slot on EVERY unpin (eviction and explicit
        # release alike), before its fields clear — the paged KV tier
        # frees the slot's block table here (kvcache/blocks.py), so
        # device blocks can never outlive the session that owned them.
        self.on_unpin = on_unpin

    def lookup(self, session_id: str) -> Slot | None:
        return self._by_session.get(session_id)

    def acquire(self, session_id: str) -> Slot | None:
        """Pin a slot for this session: existing pin → free slot → evict
        the least-recently-used idle session. None if all slots are
        actively decoding (caller queues the request)."""
        slot = self._by_session.get(session_id)
        if slot is not None:
            slot.last_used = time.monotonic()
            return slot
        for slot in self.slots:
            if slot.session_id is None:
                return self._pin(slot, session_id)
        victims = [s for s in self.slots if not s.active]
        if not victims:
            return None
        victim = min(victims, key=lambda s: s.last_used)
        if self.on_evict is not None and victim.session_id is not None:
            self.on_evict(victim)
        self._unpin(victim)
        return self._pin(victim, session_id)

    def _pin(self, slot: Slot, session_id: str) -> Slot:
        slot.session_id = session_id
        slot.tokens = []
        slot.kv_written = 0
        slot.active = False
        slot.last_used = time.monotonic()
        self._by_session[session_id] = slot
        return slot

    def _unpin(self, slot: Slot) -> None:
        if self.on_unpin is not None:
            self.on_unpin(slot)
        if slot.session_id is not None:
            self._by_session.pop(slot.session_id, None)
        slot.session_id = None
        slot.tokens = []
        slot.kv_written = 0
        slot.active = False

    def release_session(self, session_id: str) -> None:
        slot = self._by_session.get(session_id)
        if slot is not None and not slot.active:
            self._unpin(slot)
        elif slot is not None:
            # Active request: mark for release when generation finishes.
            slot.last_used = 0.0

    def reuse_prefix(self, slot: Slot, prompt_tokens: list[int]) -> int:
        """Longest reusable cached prefix for this prompt.

        Returns the number of leading prompt tokens whose KV is already in
        the slot (0 → full prefill). Never returns the full prompt length:
        at least one token must run through the model to produce logits,
        so reuse is capped at len(prompt) - 1. Also capped at kv_written —
        a kept token whose KV row was never written (request finished the
        step it was sampled) must be re-fed, not trusted.
        """
        cached = slot.tokens
        limit = min(len(cached), len(prompt_tokens) - 1, slot.kv_written)
        n = _lcp(cached, prompt_tokens, limit)
        if n < len(cached):
            # Divergence: the cache beyond n is for a different history.
            # Positions beyond n will be overwritten by the new prefill —
            # and until then nothing may trust them, so the watermark
            # drops too (best_shared_prefix reads other slots' tokens up
            # to kv_written; a stale watermark past len(tokens) crashed
            # the engine thread).
            slot.tokens = cached[:n]
            slot.kv_written = min(slot.kv_written, n)
        return n

    def best_shared_prefix(self, slot: Slot, prompt_tokens: list[int],
                           min_len: int = 16) -> tuple[Slot | None, int]:
        """Longest common prefix between this prompt and any OTHER
        slot's written KV — the cross-session case (a fleet of sessions
        sharing one system prompt re-prefilled it once per slot; the
        engine can copy the resident rows instead, engine.py
        shared-prefix path). Capped at the source's kv_written
        watermark and len(prompt) - 1; returns (None, 0) below
        ``min_len`` (a copy dispatch isn't worth a handful of rows)."""
        best, best_n = None, min_len - 1
        cap = len(prompt_tokens) - 1
        for other in self.slots:
            if other is slot or other.kv_written == 0:
                continue
            ot = other.tokens
            limit = min(other.kv_written, len(ot), cap)
            n = _lcp(ot, prompt_tokens, limit)
            if n > best_n:
                best, best_n = other, n
                if best_n >= cap:
                    break  # nothing longer is possible
        return best, (best_n if best is not None else 0)

    def active_slots(self) -> list[Slot]:
        return [s for s in self.slots if s.active]

    def stats(self) -> dict:
        return {
            "total_slots": len(self.slots),
            "active": sum(1 for s in self.slots if s.active),
            "pinned": sum(1 for s in self.slots if s.session_id is not None),
            "resident_tokens": sum(s.length for s in self.slots),
        }
