"""Decode-slot management: session pinning, prefix reuse, LRU eviction.

The continuous-batching engine decodes a fixed batch of S slots (static
shapes for XLA). Each slot owns one contiguous region of the KV cache
arrays. A *session* (WebSocket conversation) is pinned to a slot between
turns, so its KV stays resident in TPU HBM and a follow-up turn only
prefills the new tokens — the north-star requirement the reference could
not meet (its KV lived inside an external engine container and was gone
between HTTP calls; BASELINE.json north_star).

All methods are called from the engine thread only — no locks by design
(contrast: the reference's lock-discipline bugs, SURVEY.md §5 race
detection: get_detailed_stats self-deadlock).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class Slot:
    index: int
    session_id: str | None = None     # pinned session (None = free)
    tokens: list[int] = field(default_factory=list)  # kept token ids
    # How many leading entries of ``tokens`` have their KV actually
    # written in HBM. A token's KV is written when it is *fed*, one step
    # after it is sampled — so a request finishing on max_tokens keeps a
    # final token whose KV row was never written. Prefix reuse must not
    # trust rows beyond this watermark.
    kv_written: int = 0
    active: bool = False              # currently decoding a request
    last_used: float = 0.0

    @property
    def length(self) -> int:
        return len(self.tokens)


class SlotManager:
    def __init__(self, num_slots: int, max_len: int):
        self.slots = [Slot(i) for i in range(num_slots)]
        self.max_len = max_len
        self._by_session: dict[str, Slot] = {}

    def lookup(self, session_id: str) -> Slot | None:
        return self._by_session.get(session_id)

    def acquire(self, session_id: str) -> Slot | None:
        """Pin a slot for this session: existing pin → free slot → evict
        the least-recently-used idle session. None if all slots are
        actively decoding (caller queues the request)."""
        slot = self._by_session.get(session_id)
        if slot is not None:
            slot.last_used = time.monotonic()
            return slot
        for slot in self.slots:
            if slot.session_id is None:
                return self._pin(slot, session_id)
        victims = [s for s in self.slots if not s.active]
        if not victims:
            return None
        victim = min(victims, key=lambda s: s.last_used)
        self._unpin(victim)
        return self._pin(victim, session_id)

    def _pin(self, slot: Slot, session_id: str) -> Slot:
        slot.session_id = session_id
        slot.tokens = []
        slot.kv_written = 0
        slot.active = False
        slot.last_used = time.monotonic()
        self._by_session[session_id] = slot
        return slot

    def _unpin(self, slot: Slot) -> None:
        if slot.session_id is not None:
            self._by_session.pop(slot.session_id, None)
        slot.session_id = None
        slot.tokens = []
        slot.kv_written = 0
        slot.active = False

    def release_session(self, session_id: str) -> None:
        slot = self._by_session.get(session_id)
        if slot is not None and not slot.active:
            self._unpin(slot)
        elif slot is not None:
            # Active request: mark for release when generation finishes.
            slot.last_used = 0.0

    def reuse_prefix(self, slot: Slot, prompt_tokens: list[int]) -> int:
        """Longest reusable cached prefix for this prompt.

        Returns the number of leading prompt tokens whose KV is already in
        the slot (0 → full prefill). Never returns the full prompt length:
        at least one token must run through the model to produce logits,
        so reuse is capped at len(prompt) - 1. Also capped at kv_written —
        a kept token whose KV row was never written (request finished the
        step it was sampled) must be re-fed, not trusted.
        """
        cached = slot.tokens
        limit = min(len(cached), len(prompt_tokens) - 1, slot.kv_written)
        n = 0
        while n < limit and cached[n] == prompt_tokens[n]:
            n += 1
        if n < len(cached):
            # Divergence: the cache beyond n is for a different history.
            # Positions beyond n will be overwritten by the new prefill.
            slot.tokens = cached[:n]
        return n

    def active_slots(self) -> list[Slot]:
        return [s for s in self.slots if s.active]

    def stats(self) -> dict:
        return {
            "total_slots": len(self.slots),
            "active": sum(1 for s in self.slots if s.active),
            "pinned": sum(1 for s in self.slots if s.session_id is not None),
            "resident_tokens": sum(s.length for s in self.slots),
        }
