"""Tokenization: HF fast tokenizer when checkpoint files exist, byte-level
fallback otherwise, plus the Llama-3 chat template and incremental
detokenization for streaming.

The reference never tokenized — its external engines did, and its "token"
counts were actually stream-chunk counts (SURVEY.md §5 metrics gap). Here
the framework owns the tokenizer, so streamed deltas and counters are real
tokens.
"""

from __future__ import annotations

import os
from typing import Protocol, Sequence

Message = dict[str, str]  # {"role": ..., "content": ...}


class Tokenizer(Protocol):
    vocab_size: int
    eos_ids: frozenset[int]
    pad_id: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...

    def apply_chat_template(self, messages: Sequence[Message],
                            add_generation_prompt: bool = True) -> list[int]: ...


class ByteTokenizer:
    """Self-contained byte-level tokenizer (no files, no network).

    ids 0..255 = raw bytes; specials above. Role headers are single
    tokens so the chat template stays cheap and unambiguous. Used for
    tests and for weight-free benchmarking; real checkpoints bring their
    own tokenizer.json.
    """

    BOS = 256
    EOS = 257
    ROLE_SYSTEM = 258
    ROLE_USER = 259
    ROLE_ASSISTANT = 260
    ROLE_TOOL = 261
    pad_id = 262
    vocab_size = 263

    def __init__(self) -> None:
        self.eos_ids = frozenset({self.EOS})
        self._role_tokens = {
            "system": self.ROLE_SYSTEM,
            "user": self.ROLE_USER,
            "assistant": self.ROLE_ASSISTANT,
            "tool": self.ROLE_TOOL,
        }

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: Sequence[Message],
                            add_generation_prompt: bool = True) -> list[int]:
        out = [self.BOS]
        for m in messages:
            out.append(self._role_tokens.get(m.get("role", "user"), self.ROLE_USER))
            out.extend(self.encode(m.get("content", "")))
            out.append(self.EOS)
        if add_generation_prompt:
            out.append(self.ROLE_ASSISTANT)
        return out


class HFTokenizer:
    """Wraps a HuggingFace fast tokenizer (tokenizer.json) with the
    Llama-3 instruct chat template rendered in-tree (templates are not
    fetchable in a zero-egress deployment, and the format is fixed)."""

    # Llama-3 special token ids (checkpoint-defined, stable across 3.x).
    BOS_TEXT = "<|begin_of_text|>"
    HDR_START = "<|start_header_id|>"
    HDR_END = "<|end_header_id|>"
    EOT = "<|eot_id|>"

    def __init__(self, tokenizer_file: str):
        from tokenizers import Tokenizer as RustTokenizer

        self._tok = RustTokenizer.from_file(tokenizer_file)
        self.vocab_size = self._tok.get_vocab_size()
        eos = set()
        for name in ("<|eot_id|>", "<|end_of_text|>", "</s>", "<|eom_id|>"):
            tid = self._tok.token_to_id(name)
            if tid is not None:
                eos.add(tid)
        self.eos_ids = frozenset(eos) or frozenset({self.vocab_size - 1})
        pad = self._tok.token_to_id("<|finetune_right_pad_id|>")
        self.pad_id = pad if pad is not None else 0

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def _header(self, role: str) -> str:
        return f"{self.HDR_START}{role}{self.HDR_END}\n\n"

    def apply_chat_template(self, messages: Sequence[Message],
                            add_generation_prompt: bool = True) -> list[int]:
        text = self.BOS_TEXT
        for m in messages:
            text += self._header(m.get("role", "user"))
            text += m.get("content", "") + self.EOT
        if add_generation_prompt:
            text += self._header("assistant")
        return self._tok.encode(text, add_special_tokens=False).ids


class StreamDetokenizer:
    """Incremental detokenization for one stream.

    Emits only complete, stable UTF-8 text: decodes the full generated-id
    list and diffs against what was already emitted, holding back while
    the decoded text ends in a replacement char (split multi-byte/
    multi-token glyph).
    """

    # A legal UTF-8 glyph spans at most 4 bytes / a few tokens; past that,
    # a trailing replacement char is genuinely invalid output and must be
    # emitted rather than held back forever.
    MAX_HOLDBACK_TOKENS = 4

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer
        self._ids: list[int] = []
        self._emitted = 0
        self._held_since = 0

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        text = self._tok.decode(self._ids)
        if text.endswith("�") and \
                len(self._ids) - self._held_since <= self.MAX_HOLDBACK_TOKENS:
            return ""
        delta = text[self._emitted:]
        self._emitted = len(text)
        self._held_since = len(self._ids)
        return delta

    def flush(self) -> str:
        text = self._tok.decode(self._ids)
        delta = text[self._emitted:]
        self._emitted = len(text)
        return delta

    @property
    def text(self) -> str:
        return self._tok.decode(self._ids)

    @property
    def token_count(self) -> int:
        return len(self._ids)


def find_tokenizer_file(model_path: str, model_name: str) -> str | None:
    from fasttalk_tpu.models.loader import find_checkpoint_dir

    candidates = []
    ckpt = find_checkpoint_dir(model_path, model_name) if model_path else None
    if ckpt:
        candidates.append(os.path.join(ckpt, "tokenizer.json"))
    if model_path:
        candidates.append(os.path.join(model_path, "tokenizer.json"))
    for c in candidates:
        if os.path.isfile(c):
            return c
    return None


def load_tokenizer(model_path: str, model_name: str,
                   tokenizer_path: str = "") -> Tokenizer:
    """HF tokenizer if files are present, else the byte fallback."""
    tf = tokenizer_path if tokenizer_path and os.path.isfile(tokenizer_path) \
        else find_tokenizer_file(model_path, model_name)
    if tf:
        return HFTokenizer(tf)
    return ByteTokenizer()
