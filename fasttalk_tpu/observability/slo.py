"""SLO engine: per-priority-class objectives, multi-window burn rates,
alert states and goodput accounting.

PR 1 gave the service measurements (spans, phase histograms) and PR 2
gave it load control (admission, shedding) — this module closes the
loop with an explicit notion of *the latency promise* and whether the
service is currently keeping it. The pattern is standard SRE practice
scaled to serving-time windows:

- **Objectives per priority class** (scheduling/scheduler.py classes:
  ``interactive``, ``bulk``): TTFT p95, inter-token p99, queue-wait
  p95, and error rate, each an env knob (``SLO_TTFT_P95_MS``,
  ``SLO_INTER_TOKEN_P99_MS``, ``SLO_QUEUE_WAIT_P95_MS``,
  ``SLO_ERROR_RATE``). Bulk relaxes the latency targets by
  ``SLO_BULK_FACTOR`` (default 4x) unless overridden per class
  (``SLO_BULK_TTFT_P95_MS`` etc. — any base knob prefixed with the
  upper-cased class name).
- **Multi-window burn rates.** Each objective has an error budget (a
  p95 target tolerates 5% violations, a p99 target 1%, the error-rate
  target is its own budget). Burn = observed violation fraction over a
  rolling window divided by the budget; burn 1.0 means exactly
  spending the budget, 10 means burning it 10x too fast. Windows are
  1m/5m/30m (``SLO_WINDOWS_S``).
- **Alert states** (classic fast/slow pairing): ``page`` when the
  short AND mid windows both burn at ≥ ``SLO_PAGE_BURN`` (default 10 —
  a fast, severe burn), ``warn`` when the mid AND long windows both
  burn at ≥ ``SLO_WARN_BURN`` (default 2 — slow but budget-exhausting),
  else ``ok``. A window with fewer than ``SLO_MIN_SAMPLES`` completed
  requests never alerts (no paging on three unlucky requests at 4 am).
  Transitions emit ``slo_burn_start`` / ``slo_burn_stop`` events
  (observability/events.py).
- **Goodput**: the fraction of completed requests that met *every*
  objective, per class and window — the honest headline under
  overload, where raw tok/s keeps looking fine while half the users
  wait seconds for a first token. The inter-token SLI is per-request:
  a request is inter-token-good when its **worst** gap is at or under
  the target (budgeted at 1%, the p99 discipline applied per request).

Recording is one ``record_request`` call per finished request (engine
``_finish``) — O(1) append under a lock; evaluation is lazy and cached
(at most once per second unless forced), so the hot path never pays
the window math.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any

from fasttalk_tpu.observability.events import (EventLog, env_float,
                                                get_events)
from fasttalk_tpu.utils.metrics import Histogram

# One source of truth for the knob defaults; scripts/trace_report.py
# --slo mirrors these (stdlib-only, cannot import this module) and
# tests/test_slo.py pins the two tables equal.
DEFAULTS: dict[str, float] = {
    "SLO_TTFT_P95_MS": 1500.0,
    "SLO_INTER_TOKEN_P99_MS": 250.0,
    "SLO_QUEUE_WAIT_P95_MS": 1000.0,
    "SLO_ERROR_RATE": 0.01,
}
DEFAULT_BULK_FACTOR = 4.0
DEFAULT_WINDOWS_S = (60.0, 300.0, 1800.0)
DEFAULT_PAGE_BURN = 10.0
DEFAULT_WARN_BURN = 2.0
DEFAULT_MIN_SAMPLES = 20

# Error budgets implied by the objective's percentile: a p95 target
# tolerates 5% of requests over it, a p99 target 1%.
_BUDGETS = {"ttft": 0.05, "inter_token": 0.01, "queue_wait": 0.05}

ALERT_OK = "ok"
ALERT_WARN = "warn"
ALERT_PAGE = "page"
_ALERT_RANK = {ALERT_OK: 0, ALERT_WARN: 1, ALERT_PAGE: 2}


@dataclass(frozen=True)
class ClassObjectives:
    """Targets for one priority class (ms / fraction)."""
    ttft_p95_ms: float
    inter_token_p99_ms: float
    queue_wait_p95_ms: float
    error_rate: float

    def to_dict(self) -> dict[str, float]:
        return {
            "ttft_p95_ms": self.ttft_p95_ms,
            "inter_token_p99_ms": self.inter_token_p99_ms,
            "queue_wait_p95_ms": self.queue_wait_p95_ms,
            "error_rate": self.error_rate,
        }


def objectives_from_env(cls: str = "interactive") -> ClassObjectives:
    """Resolve one class's targets: per-class env override
    (``SLO_BULK_TTFT_P95_MS``) → base env (``SLO_TTFT_P95_MS``) →
    default, with bulk's latency targets relaxed by ``SLO_BULK_FACTOR``
    when only the base is set."""
    factor = 1.0
    if cls != "interactive":
        factor = max(1.0, env_float("SLO_BULK_FACTOR",
                                     DEFAULT_BULK_FACTOR))

    def knob(base_name: str, latency: bool) -> float:
        base = env_float(base_name, DEFAULTS[base_name])
        if latency:
            base *= factor
        override = f"SLO_{cls.upper()}_{base_name[len('SLO_'):]}"
        return env_float(override, base)

    return ClassObjectives(
        ttft_p95_ms=knob("SLO_TTFT_P95_MS", latency=cls != "interactive"),
        inter_token_p99_ms=knob("SLO_INTER_TOKEN_P99_MS",
                                latency=cls != "interactive"),
        queue_wait_p95_ms=knob("SLO_QUEUE_WAIT_P95_MS",
                               latency=cls != "interactive"),
        error_rate=knob("SLO_ERROR_RATE", latency=False),
    )


@dataclass
class _Sample:
    """One completed request, stamped with everything the objectives
    need. ``None`` fields mean the dimension does not apply (an errored
    request that never got a token has no TTFT; a one-token reply has
    no inter-token gap)."""
    t: float                     # monotonic completion time
    ok: bool                     # terminal done/stop/length (not error)
    good: bool                   # ok AND met every latency objective
    ttft_ms: float | None
    queue_wait_ms: float | None
    max_gap_ms: float | None


class _ClassState:
    def __init__(self, objectives: ClassObjectives):
        self.objectives = objectives
        self.samples: list[_Sample] = []
        self.alert = ALERT_OK
        self.total_requests = 0
        self.total_errors = 0
        self.total_good = 0
        self.total_shed = 0


def _window_label(seconds: float) -> str:
    if seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{int(seconds)}s"


class SLOEngine:
    """Rolling multi-window SLO evaluation over per-request samples."""

    def __init__(self, *,
                 windows_s: tuple[float, ...] | None = None,
                 page_burn: float | None = None,
                 warn_burn: float | None = None,
                 min_samples: int | None = None,
                 shed_bulk_on_page: bool | None = None,
                 clock=time.monotonic,
                 events: EventLog | None = None,
                 eval_interval_s: float = 1.0,
                 max_samples_per_class: int = 8192):
        if windows_s is None:
            raw = os.getenv("SLO_WINDOWS_S", "")
            try:
                windows_s = tuple(sorted(
                    float(x) for x in raw.split(",") if x.strip())) \
                    or DEFAULT_WINDOWS_S
            except ValueError:
                windows_s = DEFAULT_WINDOWS_S
        if len(windows_s) < 2:
            windows_s = DEFAULT_WINDOWS_S
        self.windows_s = tuple(sorted(windows_s))
        self.page_burn = page_burn if page_burn is not None \
            else env_float("SLO_PAGE_BURN", DEFAULT_PAGE_BURN)
        self.warn_burn = warn_burn if warn_burn is not None \
            else env_float("SLO_WARN_BURN", DEFAULT_WARN_BURN)
        self.min_samples = min_samples if min_samples is not None \
            else int(env_float("SLO_MIN_SAMPLES", DEFAULT_MIN_SAMPLES))
        if shed_bulk_on_page is None:
            shed_bulk_on_page = os.getenv(
                "SLO_SHED_BULK_ON_PAGE", "true").strip().lower() in (
                "1", "true", "yes", "on")
        self.shed_bulk_on_page = shed_bulk_on_page
        self._clock = clock
        self._events = events if events is not None else get_events()
        self._eval_interval = eval_interval_s
        self._max_samples = max_samples_per_class
        self._lock = threading.Lock()
        self._classes: dict[str, _ClassState] = {}
        self._last_eval = float("-inf")
        self._last_report: dict[str, dict[str, Any]] = {}

    # ---------------- recording (engine thread) ----------------

    def _class_state_locked(self, cls: str) -> _ClassState:
        st = self._classes.get(cls)
        if st is None:
            st = _ClassState(objectives_from_env(cls))
            self._classes[cls] = st
        return st

    def record_request(self, cls: str, *, ok: bool,
                       ttft_ms: float | None,
                       queue_wait_ms: float | None,
                       max_gap_ms: float | None,
                       now: float | None = None) -> None:
        """One finished request (done/stop/length or error; cancels are
        the client's choice and are not recorded)."""
        now = self._clock() if now is None else now
        with self._lock:
            st = self._class_state_locked(cls)
            o = st.objectives
            good = bool(
                ok
                and (ttft_ms is not None and ttft_ms <= o.ttft_p95_ms)
                and (queue_wait_ms is None
                     or queue_wait_ms <= o.queue_wait_p95_ms)
                and (max_gap_ms is None
                     or max_gap_ms <= o.inter_token_p99_ms))
            st.samples.append(_Sample(
                t=now, ok=ok, good=good, ttft_ms=ttft_ms,
                queue_wait_ms=queue_wait_ms, max_gap_ms=max_gap_ms))
            st.total_requests += 1
            st.total_errors += 0 if ok else 1
            st.total_good += 1 if good else 0
            self._prune_locked(st, now)

    def record_shed(self, cls: str, now: float | None = None) -> None:
        """A submission shed at admission — tracked for the snapshot,
        deliberately NOT an SLO error: shedding is the scheduler keeping
        the promise for everyone it admitted."""
        with self._lock:
            self._class_state_locked(cls).total_shed += 1

    def _prune_locked(self, st: _ClassState, now: float) -> None:
        horizon = now - self.windows_s[-1]
        samples = st.samples
        if len(samples) > self._max_samples:
            del samples[:len(samples) - self._max_samples]
        # Amortised: drop the aged head (samples arrive in time order).
        i = 0
        while i < len(samples) and samples[i].t < horizon:
            i += 1
        if i:
            del samples[:i]

    # ---------------- evaluation ----------------

    @staticmethod
    def _burn(frac_bad: float, budget: float) -> float:
        return frac_bad / budget if budget > 0 else 0.0

    def _eval_window_locked(self, st: _ClassState, now: float,
                            window_s: float) -> dict[str, Any]:
        cut = now - window_s
        sub = [s for s in st.samples if s.t >= cut]
        n = len(sub)
        o = st.objectives
        out: dict[str, Any] = {"n": n}
        if n == 0:
            out.update(goodput=None, burn={}, max_burn=0.0)
            return out
        ttfts = sorted(s.ttft_ms for s in sub if s.ttft_ms is not None)
        gaps = sorted(s.max_gap_ms for s in sub
                      if s.max_gap_ms is not None)
        waits = sorted(s.queue_wait_ms for s in sub
                       if s.queue_wait_ms is not None)
        burn: dict[str, float] = {}
        if ttfts:
            frac = sum(1 for v in ttfts if v > o.ttft_p95_ms) / len(ttfts)
            burn["ttft"] = self._burn(frac, _BUDGETS["ttft"])
            out["ttft_p95_ms"] = round(Histogram._quantile(ttfts, 95), 3)
        if gaps:
            frac = sum(1 for v in gaps
                       if v > o.inter_token_p99_ms) / len(gaps)
            burn["inter_token"] = self._burn(frac,
                                             _BUDGETS["inter_token"])
            out["inter_token_p99_ms"] = round(
                Histogram._quantile(gaps, 99), 3)
        if waits:
            frac = sum(1 for v in waits
                       if v > o.queue_wait_p95_ms) / len(waits)
            burn["queue_wait"] = self._burn(frac, _BUDGETS["queue_wait"])
            out["queue_wait_p95_ms"] = round(
                Histogram._quantile(waits, 95), 3)
        err_frac = sum(1 for s in sub if not s.ok) / n
        burn["error"] = self._burn(err_frac, o.error_rate)
        out["error_rate"] = round(err_frac, 4)
        out["goodput"] = round(sum(1 for s in sub if s.good) / n, 4)
        out["burn"] = {k: round(v, 3) for k, v in burn.items()}
        out["max_burn"] = round(max(burn.values(), default=0.0), 3)
        return out

    def _alert_from_windows_locked(
            self, windows: dict[str, dict[str, Any]]) -> tuple[str, str]:
        """(state, worst_objective): page on fast+mid burn, warn on
        mid+long burn — both windows must agree AND both must hold at
        least min_samples, so a thin window can never page alone."""
        labels = [_window_label(w) for w in self.windows_s]
        short, mid, long_ = (windows[labels[0]], windows[labels[1]],
                             windows[labels[-1]])

        def burning(w: dict[str, Any], threshold: float) -> str | None:
            if w["n"] < self.min_samples:
                return None
            over = {k: v for k, v in w.get("burn", {}).items()
                    if v >= threshold}
            if not over:
                return None
            return max(over, key=over.get)  # worst objective name

        fast = burning(short, self.page_burn)
        if fast is not None and burning(mid, self.page_burn) is not None:
            return ALERT_PAGE, fast
        slow = burning(mid, self.warn_burn)
        if slow is not None and burning(long_, self.warn_burn) is not None:
            return ALERT_WARN, slow
        return ALERT_OK, ""

    def evaluate(self, now: float | None = None,
                 force: bool = False) -> dict[str, dict[str, Any]]:
        """Recompute every class's window report and alert state,
        emitting slo_burn_start/stop events on transitions. Cached:
        callers on hot paths (scheduler gate, health) pay a dict read
        unless ``eval_interval_s`` has elapsed."""
        now = self._clock() if now is None else now
        # Transition events are collected under the lock and emitted
        # after it: emit() may mirror to a (possibly slow) EVENTS_JSONL
        # disk, and that write must never block record_request on the
        # engine's _finish hot path against this lock.
        pending: list[tuple[str, dict[str, Any]]] = []
        with self._lock:
            if not force and now - self._last_eval < self._eval_interval:
                return self._last_report
            self._last_eval = now
            report: dict[str, dict[str, Any]] = {}
            for cls, st in self._classes.items():
                self._prune_locked(st, now)
                windows = {
                    _window_label(w): self._eval_window_locked(st, now, w)
                    for w in self.windows_s}
                state, worst = self._alert_from_windows_locked(windows)
                prev = st.alert
                st.alert = state
                report[cls] = {
                    "objectives": st.objectives.to_dict(),
                    "alert": state,
                    "windows": windows,
                    "totals": {
                        "requests": st.total_requests,
                        "errors": st.total_errors,
                        "good": st.total_good,
                        "shed": st.total_shed,
                        "goodput": round(
                            st.total_good / st.total_requests, 4)
                        if st.total_requests else None,
                    },
                }
                if _ALERT_RANK[state] > _ALERT_RANK[prev]:
                    pending.append(("slo_burn_start", dict(
                        severity="critical" if state == ALERT_PAGE
                        else "warning",
                        cls=cls, state=state, objective=worst,
                        windows={k: w.get("burn", {})
                                 for k, w in windows.items()})))
                elif prev != ALERT_OK and state == ALERT_OK:
                    pending.append(("slo_burn_stop",
                                    dict(cls=cls, recovered_from=prev)))
            self._last_report = report
        for kind, kw in pending:
            self._events.emit(kind, **kw)
        return report

    # ---------------- read side ----------------

    def alert_state(self, cls: str, now: float | None = None) -> str:
        report = self.evaluate(now)
        return report.get(cls, {}).get("alert", ALERT_OK)

    def should_shed(self, priority: str,
                    now: float | None = None) -> bool:
        """Admission-control hook (scheduling/scheduler.py slo_gate):
        while the interactive class is page-burning, incoming bulk is
        shed at the door — capacity goes to the class whose promise is
        being broken. Interactive itself is never SLO-shed (the queue
        bound and deadline checks already govern it)."""
        if not self.shed_bulk_on_page or priority == "interactive":
            return False
        return self.alert_state("interactive", now) == ALERT_PAGE

    def snapshot(self, now: float | None = None) -> dict[str, Any]:
        """The ``GET /slo`` body."""
        report = self.evaluate(now, force=True)
        return {
            "windows_s": list(self.windows_s),
            "thresholds": {
                "page_burn": self.page_burn,
                "warn_burn": self.warn_burn,
                "min_samples": self.min_samples,
            },
            "shed_bulk_on_page": self.shed_bulk_on_page,
            "classes": report,
        }

    def alert_summary(self, now: float | None = None) -> dict[str, str]:
        """{class: alert_state} — the health surface's view."""
        report = self.evaluate(now)
        return {cls: body["alert"] for cls, body in report.items()}

    def clear(self) -> None:
        """Test hook: drop samples and alert state IN PLACE."""
        with self._lock:
            self._classes.clear()
            self._last_eval = float("-inf")
            self._last_report = {}


_slo: SLOEngine | None = None


def get_slo() -> SLOEngine:
    global _slo
    if _slo is None:
        _slo = SLOEngine()
    return _slo


def reset_slo() -> None:
    """Test hook: clear the process-wide SLO engine in place (modules
    cache the handle at construction, like metrics/tracer)."""
    if _slo is not None:
        _slo.clear()
