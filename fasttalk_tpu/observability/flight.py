"""Incident flight recorder: automatic evidence capture when paging.

When the SLO engine pages or the watchdog catches a stall, the
evidence an operator needs — the event tail, the trace ring, the
metrics and the attribution report AS THEY WERE at the incident — is
all in bounded in-process rings that traffic will overwrite within
minutes. Today the operator has to reproduce the incident by hand
before ``POST /profiler/start`` is any use. This module snapshots
everything the moment trouble is detected:

**Triggers** (an EventLog listener, installed by the serving layer):

- ``slo_burn_start`` with ``state: "page"`` — a broken latency promise
- ``stall_detected`` / ``watchdog_cancel`` — a hung engine step or a
  token-stalled request
- ``engine_restart`` — supervised in-process recovery ran
- a ``recompile`` burst — ``FLIGHT_RECOMPILE_BURST`` (default 5)
  serving-time compiles within ``FLIGHT_RECOMPILE_WINDOW_S`` (default
  60) — one compile is an event, a burst is a shape-churn incident
- ``POST /debug/bundle`` on the monitoring port (manual, any time)

**Bundle** — one timestamped directory under ``FLIGHT_DIR`` (default
``/tmp/fasttalk-tpu-flight``):

- ``manifest.json`` — trigger, timestamps, section errors if any
- ``events.json`` — newest-first event-ring tail
- ``slo.json`` — the full per-class SLO report
- ``perf.json`` — the attribution ledger report (observability/perf.py)
- ``metrics.prom`` / ``metrics.json`` — the metrics registry
- ``trace.json`` (Perfetto-loadable Chrome trace of the completed ring
  + engine-step row) and ``trace.jsonl``
- ``config.json`` — resolved service config with secret-shaped values
  redacted
- optionally ``xla_trace/`` — a timed ``jax.profiler`` device capture
  of the NEXT ``FLIGHT_AUTOPROF_S`` seconds (default 0 = off; skipped
  cleanly when a manual profiler trace is already active)

**Bounded and off-loop.** Writes run on a daemon thread (the trigger
may fire on the engine thread or the asyncio loop — neither may block
on disk); at most one bundle per ``FLIGHT_MIN_INTERVAL_S`` (default
120; a page storm produces ONE bundle, not a disk-filling flood);
only the newest ``FLIGHT_MAX_BUNDLES`` (default 8) directories are
kept. Every section write is individually fault-isolated — a broken
exporter costs that file, not the bundle.

Fake-clock testable: the clock is injectable and ``inline=True`` makes
trigger() write synchronously, so tests drive a synthetic page event
and assert on the bundle with zero sleeps.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any

from fasttalk_tpu.observability.events import (Event, EventLog, env_float,
                                               get_events)
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("observability.flight")

DEFAULT_DIR = "/tmp/fasttalk-tpu-flight"
DEFAULT_MAX_BUNDLES = 8
DEFAULT_MIN_INTERVAL_S = 120.0
DEFAULT_RECOMPILE_BURST = 5
DEFAULT_RECOMPILE_WINDOW_S = 60.0
DEFAULT_EVENTS_TAIL = 256

# Config keys whose values never belong in a bundle shipped to a bug
# tracker (matched as substrings of the field name).
_SECRET_MARKERS = ("key", "token", "secret", "password")


def redact_config(cfg: dict[str, Any]) -> dict[str, Any]:
    """Secret-shaped values → "***". The exemption is by FIELD NAME
    (`*_path` / `*_dir`, e.g. tokenizer_path carries "token" but is a
    path), never by value shape — a slash inside a credential (base64,
    JWT segments) must not smuggle it into a shareable bundle."""
    out: dict[str, Any] = {}
    for k, v in cfg.items():
        lk = k.lower()
        if any(m in lk for m in _SECRET_MARKERS) \
                and not lk.endswith(("_path", "_dir")) \
                and isinstance(v, str) and v:
            out[k] = "***"
        else:
            out[k] = v
    return out


class FlightRecorder:
    """Event-triggered debug-bundle writer; process-wide singleton in
    serving (get_flight), standalone-constructible in tests."""

    def __init__(self, *, enabled: bool | None = None,
                 base_dir: str | None = None,
                 max_bundles: int | None = None,
                 min_interval_s: float | None = None,
                 autoprof_s: float | None = None,
                 recompile_burst: int | None = None,
                 recompile_window_s: float | None = None,
                 events_tail: int | None = None,
                 clock=time.time,
                 inline: bool = False,
                 config_provider=None):
        if enabled is None:
            enabled = os.getenv("FLIGHT_ENABLED", "true").strip().lower() \
                in ("1", "true", "yes", "on")
        self.enabled = enabled
        self.base_dir = base_dir if base_dir is not None \
            else (os.getenv("FLIGHT_DIR", "").strip() or DEFAULT_DIR)
        self.max_bundles = max_bundles if max_bundles is not None \
            else max(1, int(env_float("FLIGHT_MAX_BUNDLES",
                                      DEFAULT_MAX_BUNDLES)))
        self.min_interval_s = min_interval_s \
            if min_interval_s is not None \
            else max(0.0, env_float("FLIGHT_MIN_INTERVAL_S",
                                    DEFAULT_MIN_INTERVAL_S))
        self.autoprof_s = autoprof_s if autoprof_s is not None \
            else max(0.0, env_float("FLIGHT_AUTOPROF_S", 0.0))
        self.recompile_burst = recompile_burst \
            if recompile_burst is not None \
            else max(2, int(env_float("FLIGHT_RECOMPILE_BURST",
                                      DEFAULT_RECOMPILE_BURST)))
        self.recompile_window_s = recompile_window_s \
            if recompile_window_s is not None \
            else max(1.0, env_float("FLIGHT_RECOMPILE_WINDOW_S",
                                    DEFAULT_RECOMPILE_WINDOW_S))
        self.events_tail = events_tail if events_tail is not None \
            else max(1, int(env_float("FLIGHT_EVENTS_TAIL",
                                      DEFAULT_EVENTS_TAIL)))
        self._clock = clock
        self._inline = inline
        self._config_provider = config_provider
        self._lock = threading.Lock()
        self._last_bundle_ts: float | None = None
        self._writing = False
        self._recompile_ts: list[float] = []
        self._installed_on: EventLog | None = None
        self.bundles_written = 0
        self.triggers_suppressed = 0

    # ---------------- wiring ----------------

    def install(self, events: EventLog | None = None) -> None:
        """Subscribe to the event log (idempotent)."""
        events = events if events is not None else get_events()
        events.add_listener(self.on_event)
        self._installed_on = events

    def uninstall(self) -> None:
        if self._installed_on is not None:
            self._installed_on.remove_listener(self.on_event)
            self._installed_on = None

    # ---------------- triggers ----------------

    def on_event(self, ev: Event) -> None:
        """EventLog listener: map incident-class events to bundles.
        Runs on the emitter's thread — every path here is O(1) checks
        plus, at most, spawning the writer thread."""
        if not self.enabled:
            return
        kind = ev.kind
        if kind == "slo_burn_start":
            if ev.attrs.get("state") == "page":
                self.trigger(f"slo_page:{ev.attrs.get('cls', '?')}",
                             kind=kind)
        elif kind in ("stall_detected", "watchdog_cancel",
                      "engine_restart", "router_failover",
                      "router_partition"):
            # router_failover: a replica died with a stream on it — the
            # evidence (events, traces, per-replica stats) is exactly
            # what the post-mortem needs and is gone minutes later.
            # router_partition: the probe-death flavour — the replica
            # may be healthy but unreachable; the bundle captures the
            # router's view before recovery overwrites it.
            self.trigger(kind, kind=kind)
        elif kind == "recompile":
            now = self._clock()
            with self._lock:
                self._recompile_ts.append(now)
                horizon = now - self.recompile_window_s
                self._recompile_ts = [t for t in self._recompile_ts
                                      if t >= horizon]
                burst = len(self._recompile_ts) >= self.recompile_burst
                if burst:
                    self._recompile_ts.clear()
            if burst:
                self.trigger("recompile_burst", kind=kind)

    def trigger(self, reason: str, kind: str = "manual",
                force: bool = False, now: float | None = None,
                ) -> str | None:
        """Request a bundle. Returns the bundle directory (claimed
        synchronously; contents written off-thread unless inline) or
        None when disabled, rate-limited, or already writing. ``force``
        (the manual endpoint) bypasses the rate limit WITHOUT consuming
        it — an operator's curl must never eat the window a real
        incident needs minutes later — but never bypasses the
        in-progress guard."""
        if not self.enabled:
            return None
        now = self._clock() if now is None else now
        with self._lock:
            if self._writing:
                self.triggers_suppressed += 1
                return None
            if not force and self._last_bundle_ts is not None \
                    and now - self._last_bundle_ts < self.min_interval_s:
                self.triggers_suppressed += 1
                return None
            self._writing = True
        try:
            stamp = time.strftime("%Y%m%d-%H%M%S",
                                  time.localtime(time.time()))
            bundle_dir = os.path.join(
                self.base_dir, f"{stamp}-{self.bundles_written:03d}")
            os.makedirs(bundle_dir, exist_ok=True)
        except OSError as e:
            # Nothing was written: do NOT consume the rate limit — a
            # transiently unwritable disk must not also suppress the
            # next real incident's capture.
            log.error(f"flight bundle dir failed: {e}")
            with self._lock:
                self._writing = False
            return None
        if not force:
            # Consume the window only once a bundle dir actually
            # exists, and only for automatic triggers.
            with self._lock:
                self._last_bundle_ts = now
        if self._inline:
            self._write_bundle(bundle_dir, reason, kind, now)
        else:
            threading.Thread(
                target=self._write_bundle, name="flight-recorder",
                args=(bundle_dir, reason, kind, now), daemon=True,
            ).start()
        return bundle_dir

    # ---------------- the bundle ----------------

    def _write_bundle(self, bundle_dir: str, reason: str, kind: str,
                      now: float) -> None:
        t0 = time.monotonic()
        errors: dict[str, str] = {}

        def section(name: str, build) -> None:
            try:
                payload = build()
                with open(os.path.join(bundle_dir, name), "w",
                          encoding="utf-8") as fp:
                    if isinstance(payload, str):
                        fp.write(payload)
                    else:
                        json.dump(payload, fp, ensure_ascii=False,
                                  default=str, indent=1)
            except Exception as e:  # one broken exporter costs one file
                errors[name] = str(e)

        def events_tail():
            # Snapshot the log the recorder is subscribed to (the one
            # that carries the triggering event); the process singleton
            # when triggered manually without an install.
            src = self._installed_on if self._installed_on is not None \
                else get_events()
            return src.recent(limit=self.events_tail)

        def slo_report():
            from fasttalk_tpu.observability.slo import get_slo

            return get_slo().snapshot()

        def perf_report():
            from fasttalk_tpu.observability.perf import get_perf

            return get_perf().report()

        def metrics_prom():
            from fasttalk_tpu.utils.metrics import get_metrics

            return get_metrics().prometheus()

        def metrics_json():
            from fasttalk_tpu.utils.metrics import get_metrics

            return get_metrics().to_dict()

        def trace_chrome():
            from fasttalk_tpu.observability.export import chrome_trace
            from fasttalk_tpu.observability.trace import get_tracer

            tr = get_tracer()
            return chrome_trace(tr, tr.completed(), tr.steps())

        def trace_jsonl():
            from fasttalk_tpu.observability.export import jsonl_dump
            from fasttalk_tpu.observability.trace import get_tracer

            tr = get_tracer()
            return jsonl_dump(tr, tr.completed(), tr.steps())

        def config_redacted():
            if self._config_provider is not None:
                raw = self._config_provider()
            else:
                from fasttalk_tpu.utils.config import get_config

                raw = get_config().to_dict()
            return redact_config(dict(raw))

        def profile_collapsed():
            from fasttalk_tpu.observability.profiler import get_profiler

            return get_profiler().collapsed()

        def profile_report():
            from fasttalk_tpu.observability.profiler import get_profiler

            return get_profiler().report()

        try:
            section("events.json", events_tail)
            section("slo.json", slo_report)
            section("perf.json", perf_report)
            section("metrics.prom", metrics_prom)
            section("metrics.json", metrics_json)
            section("trace.json", trace_chrome)
            section("trace.jsonl", trace_jsonl)
            section("config.json", config_redacted)
            # What every thread was DOING when the incident fired —
            # the continuous sampler's aggregate (collapsed text for
            # flamegraph tooling + the structured report). Disabled
            # profiler still writes honest (empty) sections.
            section("profile.txt", profile_collapsed)
            section("profile.json", profile_report)
            autoprof = None
            if self.autoprof_s > 0:
                autoprof = self._autoprof(bundle_dir, errors)
            manifest = {
                "reason": reason,
                "trigger_kind": kind,
                "ts": time.time(),
                "trigger_clock": now,
                "write_s": round(time.monotonic() - t0, 3),
                "autoprof": autoprof,
                **({"errors": errors} if errors else {}),
            }
            try:
                with open(os.path.join(bundle_dir, "manifest.json"),
                          "w", encoding="utf-8") as fp:
                    json.dump(manifest, fp, indent=1, default=str)
            except OSError as e:
                log.error(f"flight manifest failed: {e}")
            self.bundles_written += 1
            self._prune()
            log.warning(
                f"flight bundle written: {bundle_dir} (reason "
                f"{reason}{', errors ' + str(sorted(errors)) if errors else ''})")
        finally:
            with self._lock:
                self._writing = False

    def _autoprof(self, bundle_dir: str,
                  errors: dict[str, str]) -> dict[str, Any] | None:
        """Timed XLA device capture into the bundle (worker thread —
        the sleep never touches the event loop). Skipped cleanly when
        a manual /profiler trace is already running (jax raises)."""
        trace_dir = os.path.join(bundle_dir, "xla_trace")
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
        except Exception as e:
            errors["xla_trace"] = str(e)
            return None
        try:
            time.sleep(self.autoprof_s)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                errors["xla_trace"] = str(e)
                return None
        return {"dir": trace_dir, "duration_s": self.autoprof_s}

    def _prune(self) -> None:
        """Keep only the newest max_bundles directories."""
        try:
            entries = sorted(
                d for d in os.listdir(self.base_dir)
                if os.path.isdir(os.path.join(self.base_dir, d)))
        except OSError:
            return
        for stale in entries[:max(0, len(entries) - self.max_bundles)]:
            shutil.rmtree(os.path.join(self.base_dir, stale),
                          ignore_errors=True)

    # ---------------- read side ----------------

    def list_bundles(self) -> list[str]:
        try:
            return sorted(
                os.path.join(self.base_dir, d)
                for d in os.listdir(self.base_dir)
                if os.path.isdir(os.path.join(self.base_dir, d)))
        except OSError:
            return []

    def stats(self) -> dict[str, Any]:
        with self._lock:
            last = self._last_bundle_ts
        return {
            "enabled": self.enabled,
            "dir": self.base_dir,
            "bundles_written": self.bundles_written,
            "triggers_suppressed": self.triggers_suppressed,
            "last_bundle_ts": last,
            "min_interval_s": self.min_interval_s,
            "max_bundles": self.max_bundles,
            "autoprof_s": self.autoprof_s,
        }

    def clear(self) -> None:
        """Test hook: detach and drop trigger state IN PLACE (written
        bundles are left on disk — they are the product, not state)."""
        self.uninstall()
        with self._lock:
            self._last_bundle_ts = None
            self._writing = False
            self._recompile_ts.clear()
        self.bundles_written = 0
        self.triggers_suppressed = 0


_flight: FlightRecorder | None = None


def get_flight() -> FlightRecorder:
    global _flight
    if _flight is None:
        _flight = FlightRecorder()
    return _flight


def reset_flight() -> None:
    """Test hook: detach the process-wide recorder and clear its
    trigger state in place."""
    if _flight is not None:
        _flight.clear()
