"""Cross-replica trace stitching (docs/OBSERVABILITY.md "Fleet
tracing").

A request that the router places on replica A, loses to a mid-stream
death, and resumes on replica B leaves its story in up to three
per-process span rings: the router-front process (router + serving
spans, and — for in-proc replicas — the replica spans too, since they
share ONE process tracer), replica A's process and replica B's. This
module defines the wire form of one process's contribution (a
*fragment*: the trace rendered with wall-clock timestamps, so rings
anchored to different monotonic clocks merge) and the join
(``stitch``): one timeline, spans tagged by source, ordered by wall
time, with the resume/terminal accounting the failover tests assert
on.

Fragments travel over ``GET /traces/{request_id}`` on the serving
port (serving/server.py); the router fans the lookup out to every
live replica (router/replica.py fetch_trace, router/router.py
stitched_trace) and the monitoring port's ``/traces/{request_id}``
falls back to the stitched view when the local ring misses — the fix
for the router-fronted 404.
"""

from __future__ import annotations

from typing import Any

from fasttalk_tpu.observability.trace import RequestTrace, Tracer

# Span names that mark a request's terminal serving event. Only the
# serving edge that owns the WS/HTTP stream emits request_complete —
# a stitched trace must contain exactly ONE, however many replicas
# the request visited.
TERMINAL_SPAN = "request_complete"
RESUME_SPAN = "resume"


def trace_fragment(tracer: Tracer, trace: RequestTrace,
                   source: str = "") -> dict[str, Any]:
    """One process's contribution to a fleet trace, in wall-clock
    time (``tracer.to_wall``) so fragments from processes with
    unrelated monotonic anchors order correctly when merged."""
    return {
        "request_id": trace.request_id,
        "session_id": trace.session_id,
        "trace_id": trace.trace_id,
        "phase": trace.phase,
        "finished": trace.finished,
        "dropped_spans": trace.dropped_spans,
        "source": source,
        "attrs": dict(trace.attrs),
        "spans": [{
            "name": s.name,
            "t0": tracer.to_wall(s.t0),
            "t1": tracer.to_wall(s.t1),
            "dur_ms": s.dur_ms,
            "attrs": dict(s.attrs),
        } for s in trace.spans],
    }


def collect_fragments(tracer: Tracer, request_id: str,
                      trace_id: str = "",
                      source: str = "") -> list[dict[str, Any]]:
    """Every local fragment for a request: exact request-id match
    first, then any other trace sharing the fleet trace id (a
    failed-over request re-dispatched under a new local request id on
    this replica)."""
    out: list[dict[str, Any]] = []
    seen: set[int] = set()
    trace = tracer.get(request_id)
    if trace is not None:
        seen.add(id(trace))
        out.append(trace_fragment(tracer, trace, source))
        trace_id = trace_id or trace.trace_id
    for t in tracer.find_by_trace_id(trace_id):
        if id(t) not in seen:
            seen.add(id(t))
            out.append(trace_fragment(tracer, t, source))
    return out


def stitch(fragments: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Merge per-process fragments into ONE cross-replica timeline.

    Spans are tagged with their fragment's source (kept as the span's
    ``component`` attr when the span already carries one — in-proc
    fleets tag at record time, remote fragments at fetch time) and
    ordered by wall-clock start. The summary counts are what the
    failover acceptance asserts: one ``resumed`` marker per failover
    and exactly one terminal event however many replicas served."""
    fragments = [f for f in fragments if f]
    if not fragments:
        return None
    spans: list[dict[str, Any]] = []
    sources: list[str] = []
    request_ids: list[str] = []
    trace_id = ""
    session_id = ""
    finished = False
    for frag in fragments:
        src = frag.get("source") or ""
        if src and src not in sources:
            sources.append(src)
        rid = frag.get("request_id") or ""
        if rid and rid not in request_ids:
            request_ids.append(rid)
        trace_id = trace_id or frag.get("trace_id") or ""
        session_id = session_id or frag.get("session_id") or ""
        finished = finished or bool(frag.get("finished"))
        for s in frag.get("spans", ()):
            row = dict(s)
            attrs = dict(row.get("attrs") or {})
            attrs.setdefault("component", src)
            row["attrs"] = attrs
            row["source"] = src
            spans.append(row)
    spans.sort(key=lambda s: (float(s.get("t0", 0.0)),
                              float(s.get("t1", 0.0))))
    components = sorted({str(s["attrs"].get("component") or "")
                         for s in spans} - {""})
    resumes = sum(1 for s in spans if s["name"] == RESUME_SPAN)
    terminals = sum(1 for s in spans if s["name"] == TERMINAL_SPAN)
    return {
        "trace_id": trace_id,
        "request_ids": request_ids,
        "session_id": session_id,
        "sources": sources,
        "components": components,
        "fragments": len(fragments),
        "finished": finished,
        "resumed": resumes,
        "terminal_events": terminals,
        "n_spans": len(spans),
        "spans": spans,
    }
