from fasttalk_tpu.observability.trace import (RequestTrace, Span, Tracer,
                                              bind_request, get_tracer,
                                              reset_tracer)
from fasttalk_tpu.observability.export import (chrome_trace, jsonl_dump,
                                               load_jsonl)
from fasttalk_tpu.observability.events import (Event, EventLog, get_events,
                                               reset_events)
from fasttalk_tpu.observability.slo import (ClassObjectives, SLOEngine,
                                            get_slo, objectives_from_env,
                                            reset_slo)
from fasttalk_tpu.observability.watchdog import (Watchdog, get_watchdog,
                                                 reset_watchdog)
from fasttalk_tpu.observability.perf import (PerfLedger, get_perf,
                                             reset_perf)
from fasttalk_tpu.observability.flight import (FlightRecorder, get_flight,
                                               reset_flight)

__all__ = [
    "Span", "RequestTrace", "Tracer", "get_tracer", "reset_tracer",
    "bind_request", "chrome_trace", "jsonl_dump", "load_jsonl",
    "Event", "EventLog", "get_events", "reset_events",
    "ClassObjectives", "SLOEngine", "get_slo", "objectives_from_env",
    "reset_slo", "Watchdog", "get_watchdog", "reset_watchdog",
    "PerfLedger", "get_perf", "reset_perf",
    "FlightRecorder", "get_flight", "reset_flight",
]
