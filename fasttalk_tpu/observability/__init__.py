from fasttalk_tpu.observability.trace import (RequestTrace, Span, Tracer,
                                              bind_request, get_tracer,
                                              reset_tracer)
from fasttalk_tpu.observability.export import (chrome_trace, jsonl_dump,
                                               load_jsonl)

__all__ = [
    "Span", "RequestTrace", "Tracer", "get_tracer", "reset_tracer",
    "bind_request", "chrome_trace", "jsonl_dump", "load_jsonl",
]
