from fasttalk_tpu.observability.trace import (RequestTrace, Span, Tracer,
                                              bind_request,
                                              current_trace_id,
                                              current_traceparent,
                                              get_tracer, make_traceparent,
                                              mint_trace_id,
                                              parse_traceparent,
                                              propagate_enabled,
                                              reset_tracer)
from fasttalk_tpu.observability.export import (chrome_trace, jsonl_dump,
                                               load_jsonl, merge_prometheus)
from fasttalk_tpu.observability.stitch import (RESUME_SPAN, TERMINAL_SPAN,
                                               collect_fragments, stitch,
                                               trace_fragment)
from fasttalk_tpu.observability.journey import (HOPS, JourneyRecorder)
from fasttalk_tpu.observability.events import (Event, EventLog, get_events,
                                               reset_events)
from fasttalk_tpu.observability.slo import (ClassObjectives, SLOEngine,
                                            get_slo, objectives_from_env,
                                            reset_slo)
from fasttalk_tpu.observability.watchdog import (Watchdog, get_watchdog,
                                                 reset_watchdog)
from fasttalk_tpu.observability.perf import (PerfLedger, get_perf,
                                             reset_perf)
from fasttalk_tpu.observability.flight import (FlightRecorder, get_flight,
                                               reset_flight)
from fasttalk_tpu.observability.fleetflight import FleetFlightRecorder

__all__ = [
    "Span", "RequestTrace", "Tracer", "get_tracer", "reset_tracer",
    "bind_request", "current_trace_id", "current_traceparent",
    "make_traceparent", "mint_trace_id", "parse_traceparent",
    "propagate_enabled", "chrome_trace", "jsonl_dump", "load_jsonl",
    "merge_prometheus", "RESUME_SPAN", "TERMINAL_SPAN",
    "collect_fragments", "stitch", "trace_fragment",
    "HOPS", "JourneyRecorder",
    "Event", "EventLog", "get_events", "reset_events",
    "ClassObjectives", "SLOEngine", "get_slo", "objectives_from_env",
    "reset_slo", "Watchdog", "get_watchdog", "reset_watchdog",
    "PerfLedger", "get_perf", "reset_perf",
    "FlightRecorder", "get_flight", "reset_flight",
    "FleetFlightRecorder",
]
