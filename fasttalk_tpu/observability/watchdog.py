"""Stall watchdog: turns "no forward progress" into a detected event.

A serving stack can hang in ways none of the existing surfaces notice:
the engine thread blocks forever inside a device call (every WebSocket
goes silent but /health still says healthy), a single request stops
producing tokens while the loop spins on (its client waits on a socket
that will never speak again), or the asyncio serving loop itself falls
seconds behind (token frames stall even though the engine is fine).
This module watches all three:

- **Engine-step heartbeat.** The engine loop stamps a monotonic float
  every iteration (engine.heartbeat_age()); while the engine has
  pending work, a heartbeat older than ``WATCHDOG_STEP_STALL_S``
  (default 15 s) is a hung step — a ``stall_detected`` event is
  emitted and /health turns degraded until it recovers.
- **Per-request token stalls.** The engine's per-request progress
  stamps (engine.progress_report()) expose how long each admitted
  request has gone without a token. Past ``WATCHDOG_TOKEN_STALL_S``
  (default 30 s) the request is flagged; past
  ``WATCHDOG_CANCEL_STALL_S`` (default 2x) it is terminated through
  engine.force_fail() with a proper terminal error frame — the client
  gets ``code: "stalled"`` plus a ``watchdog_cancel`` event, instead
  of a silent WebSocket.
- **Serving-event-loop lag.** The watchdog's own tick measures how
  late ``asyncio.sleep`` fires; the excess lands in the
  ``event_loop_lag_ms`` histogram and, past ``WATCHDOG_LOOP_LAG_MS``
  (default 500), emits a coalesced ``loop_lag`` event.

Everything is duck-typed against the engine (getattr), so FakeEngine
and the remote providers — which have no engine thread to hang — are
simply unwatched. The clock is injectable and ``check()`` is a plain
method, so tests drive synthetic stalls with a fake clock and zero
real sleeps.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any

from fasttalk_tpu.observability.events import (EventLog, env_float,
                                                get_events)
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("observability.watchdog")


class Watchdog:
    """Progress watchdog over one engine; process-wide singleton in
    serving (get_watchdog), standalone-constructible in tests."""

    def __init__(self, *,
                 token_stall_s: float | None = None,
                 step_stall_s: float | None = None,
                 cancel_stall_s: float | None = None,
                 interval_s: float | None = None,
                 loop_lag_warn_ms: float | None = None,
                 clock=time.monotonic,
                 events: EventLog | None = None):
        self.token_stall_s = token_stall_s if token_stall_s is not None \
            else env_float("WATCHDOG_TOKEN_STALL_S", 30.0)
        self.step_stall_s = step_stall_s if step_stall_s is not None \
            else env_float("WATCHDOG_STEP_STALL_S", 15.0)
        self.cancel_stall_s = cancel_stall_s \
            if cancel_stall_s is not None \
            else env_float("WATCHDOG_CANCEL_STALL_S",
                            2.0 * self.token_stall_s)
        if self.cancel_stall_s < self.token_stall_s:
            # Cancellation can never precede detection (_check_tokens
            # only examines requests past the detection threshold), so
            # a smaller cancel threshold would silently mean
            # max(token, cancel). Honor the operator's intent instead:
            # detect AND cancel at the cancel threshold.
            log.warning(
                f"WATCHDOG_CANCEL_STALL_S ({self.cancel_stall_s}s) < "
                f"WATCHDOG_TOKEN_STALL_S ({self.token_stall_s}s); "
                "lowering the detection threshold to match — stalled "
                "requests are terminated as soon as they are flagged")
            self.token_stall_s = self.cancel_stall_s
        self.interval_s = interval_s if interval_s is not None \
            else max(0.05, env_float("WATCHDOG_INTERVAL_S", 1.0))
        self.loop_lag_warn_ms = loop_lag_warn_ms \
            if loop_lag_warn_ms is not None \
            else env_float("WATCHDOG_LOOP_LAG_MS", 500.0)
        self._clock = clock
        self._events = events if events is not None else get_events()
        self._lock = threading.Lock()
        self._engine: Any = None
        self._step_stalled = False
        self._token_stalled: dict[str, float] = {}  # rid -> detected at
        # Requests already force-failed: the engine thread may stay
        # blocked (unable to process the cancel) for many more ticks,
        # and each one would otherwise re-detect and re-terminate the
        # same request — duplicate frames, spammed events.
        self._cancelled: set[str] = set()
        m = get_metrics()
        self._m_loop_lag = m.histogram(
            "event_loop_lag_ms",
            "serving event loop scheduling lag per watchdog tick "
            "(how late asyncio.sleep fired)",
            buckets=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 10000))
        self._m_hb_age = m.gauge(
            "engine_step_heartbeat_age_s",
            "seconds since the engine loop last completed an iteration")
        self._m_degraded = m.gauge(
            "watchdog_degraded",
            "1 while the watchdog sees a stalled engine step or "
            "token-stalled requests")
        self._m_stalls = m.counter(
            "watchdog_stalls_total",
            "stall detections (engine-step and per-request token "
            "stalls)")
        self._m_cancelled = m.counter(
            "watchdog_cancelled_total",
            "hopelessly stalled requests terminated by the watchdog")

    # ---------------- wiring ----------------

    def bind_engine(self, engine: Any) -> None:
        """Attach the engine to watch (duck-typed: engines without
        heartbeat/progress surfaces are left unwatched)."""
        with self._lock:
            if engine is not self._engine:
                self._engine = engine
                self._step_stalled = False
                self._token_stalled.clear()
                self._cancelled.clear()

    def heartbeat_age(self, now: float | None = None) -> float | None:
        engine = self._engine
        fn = getattr(engine, "heartbeat_age", None)
        if fn is None:
            return None
        try:
            return fn(now)
        except TypeError:
            return fn()
        except Exception:
            return None

    def sample(self) -> None:
        """Cheap gauge refresh (called by the monitoring app before
        rendering /metrics, so the heartbeat age is visible to scrapers
        even before the watchdog trips)."""
        age = self.heartbeat_age()
        if age is not None:
            self._m_hb_age.set(round(age, 3))

    # ---------------- the check ----------------

    def check(self, now: float | None = None) -> dict[str, Any]:
        """One watchdog pass; returns (and stores) the status dict."""
        now = self._clock() if now is None else now
        engine = self._engine
        step_stalled = False
        hb_age = None
        if engine is not None:
            hb_age = self.heartbeat_age(now)
            if hb_age is not None:
                self._m_hb_age.set(round(hb_age, 3))
                pending = getattr(engine, "pending_requests",
                                  lambda: 0)()
                step_stalled = bool(pending > 0
                                    and hb_age > self.step_stall_s)
        with self._lock:
            was = self._step_stalled
            self._step_stalled = step_stalled
        if step_stalled and not was:
            self._m_stalls.inc()
            self._events.emit(
                "stall_detected", severity="critical",
                stall="engine_step", heartbeat_age_s=round(hb_age, 3),
                threshold_s=self.step_stall_s)
            log.critical(f"engine step loop stalled: no iteration for "
                         f"{hb_age:.1f}s with pending work")
        elif was and not step_stalled:
            self._events.emit("stall_cleared", stall="engine_step")
            log.warning("engine step loop recovered")

        token_stalled = self._check_tokens(engine, now)

        degraded = step_stalled or bool(token_stalled)
        self._m_degraded.set(1.0 if degraded else 0.0)
        return {
            "ok": not degraded,
            "step_stalled": step_stalled,
            "heartbeat_age_s": round(hb_age, 3)
            if hb_age is not None else None,
            "token_stalled": token_stalled,
        }

    def _check_tokens(self, engine: Any,
                      now: float) -> list[dict[str, Any]]:
        report_fn = getattr(engine, "progress_report", None)
        if report_fn is None:
            with self._lock:
                self._token_stalled.clear()
            return []
        try:
            report = report_fn(now)
        except TypeError:
            report = report_fn()
        except Exception as e:
            log.error(f"progress_report failed: {e}")
            return []
        stalled: list[dict[str, Any]] = []
        seen: set[str] = set()
        for entry in report:
            rid = entry.get("request_id", "")
            age = float(entry.get("no_progress_s", 0.0))
            seen.add(rid)
            with self._lock:
                if rid in self._cancelled:
                    continue  # terminated; engine just hasn't reaped it
                known = rid in self._token_stalled
            if age <= self.token_stall_s:
                if known:
                    with self._lock:
                        self._token_stalled.pop(rid, None)
                    self._events.emit("stall_cleared", stall="token",
                                      request_id=rid)
                continue
            if not known:
                with self._lock:
                    self._token_stalled[rid] = now
                self._m_stalls.inc()
                self._events.emit(
                    "stall_detected", severity="warning", stall="token",
                    request_id=rid,
                    session_id=entry.get("session_id", ""),
                    phase=entry.get("phase", ""),
                    no_token_for_s=round(age, 3),
                    threshold_s=self.token_stall_s)
                log.warning(f"request {rid} token-stalled: no token "
                            f"for {age:.1f}s")
            if age > self.cancel_stall_s:
                self._cancel_stalled(engine, rid, age)
                continue
            stalled.append({"request_id": rid,
                            "no_token_for_s": round(age, 3)})
        # Requests that vanished from the report (finished, cancelled)
        # stop being tracked without a cleared event — their terminal
        # frame already told the story.
        with self._lock:
            for rid in list(self._token_stalled):
                if rid not in seen:
                    self._token_stalled.pop(rid, None)
            self._cancelled &= seen
        return stalled

    def _cancel_stalled(self, engine: Any, rid: str, age: float) -> None:
        with self._lock:
            self._cancelled.add(rid)
        fail = getattr(engine, "force_fail", None)
        ok = False
        if fail is not None:
            try:
                ok = bool(fail(
                    rid,
                    error=f"no forward progress for {age:.0f}s; "
                    "terminated by the stall watchdog",
                    code="stalled"))
            except Exception as e:
                log.error(f"force_fail({rid}) raised: {e}")
        if ok:
            self._m_cancelled.inc()
            self._events.emit("watchdog_cancel", severity="critical",
                              request_id=rid,
                              no_token_for_s=round(age, 3))
            log.error(f"request {rid} cancelled by the stall watchdog "
                      f"after {age:.1f}s without progress")
        with self._lock:
            self._token_stalled.pop(rid, None)

    # ---------------- status / loop ----------------

    def status(self, now: float | None = None) -> dict[str, Any]:
        """Health-surface view without re-running detection: the
        flags the last check() left behind."""
        hb_age = self.heartbeat_age(now)
        with self._lock:
            step = self._step_stalled
            tokens = list(self._token_stalled)
        return {
            "ok": not (step or tokens),
            "step_stalled": step,
            "heartbeat_age_s": round(hb_age, 3)
            if hb_age is not None else None,
            "token_stalled": tokens,
        }

    def note_loop_lag(self, lag_ms: float) -> None:
        self._m_loop_lag.observe(max(0.0, lag_ms))
        if lag_ms > self.loop_lag_warn_ms:
            self._events.emit("loop_lag", severity="warning",
                              coalesce_s=30.0,
                              lag_ms=round(lag_ms, 1),
                              threshold_ms=self.loop_lag_warn_ms)

    async def run(self) -> None:
        """The serving-side loop (started by the server at startup):
        tick, measure our own scheduling lag, check. Uses the real
        clock by construction — tests call check() directly."""
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.interval_s)
            lag_ms = (time.monotonic() - t0 - self.interval_s) * 1000.0
            self.note_loop_lag(lag_ms)
            try:
                self.check()
            except Exception as e:  # the watchdog must not die quietly
                log.error(f"watchdog check failed: {e}", exc_info=True)

    def clear(self) -> None:
        """Test hook: unbind and drop all detection state IN PLACE."""
        with self._lock:
            self._engine = None
            self._step_stalled = False
            self._token_stalled.clear()
            self._cancelled.clear()
        self._m_degraded.set(0.0)


_watchdog: Watchdog | None = None


def get_watchdog() -> Watchdog:
    global _watchdog
    if _watchdog is None:
        _watchdog = Watchdog()
    return _watchdog


def reset_watchdog() -> None:
    """Test hook: clear the process-wide watchdog in place."""
    if _watchdog is not None:
        _watchdog.clear()
