"""Request-lifecycle span tracing for the whole serving stack.

The reference gateway's only observability was wall-clock log lines
(SURVEY.md §5); our metrics registry gives aggregates but cannot answer
*where* one slow request spent its time — queue wait, prefill, decode
steps, detokenize, or the WebSocket send. This module records exactly
that: lightweight named spans (monotonic-clock start/end + attrs) per
request, collected into a bounded per-process ring buffer of completed
traces, plus a separate ring of engine-step records (per retired decode
call, with batch-occupancy / slot-utilization / spec accept counts).

Design constraints, in priority order:

- **Cheap.** The engine thread touches the tracer on admission,
  activation, retirement and finish — never per token. Every public
  method is a no-op when tracing is disabled (``TRACE_ENABLED=0``), and
  the enabled path is one lock + one list append.
- **Thread-safe.** Spans arrive from the asyncio serving loop AND the
  engine thread for the same request; a single process-wide lock
  serialises them (contention is negligible at these call rates).
- **Correlated.** ``bind_request`` sets the same ContextVar the logger
  reads (utils/logger.request_id_var), so every log line inside a bound
  task carries the request id — one id from WS frame to decode step.

Timestamps are ``time.monotonic()`` (robust to clock steps); the tracer
keeps one process-wide (wall, monotonic) anchor pair so exporters can
render absolute wall-clock times.

Export (Chrome trace-event JSON for Perfetto, JSONL for offline
analysis) lives in observability/export.py; HTTP download endpoints in
monitoring/monitor.py; the offline percentile report in
scripts/trace_report.py.
"""

from __future__ import annotations

import contextvars
import os
import re
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator

from fasttalk_tpu.utils.logger import request_id_var

# Hard cap on spans kept per trace: a runaway generation (thousands of
# decode calls) must not grow one trace without bound. Overflow is
# counted on the trace so the export can say what was dropped.
_MAX_SPANS_PER_TRACE = 2048

# ---------------------------------------------------------------------
# Trace-context propagation (docs/OBSERVABILITY.md "Fleet tracing").
#
# A trace id is minted once at the serving edge (WS accept / OpenAI
# request) and threaded through every hop after that: the ContextVar
# carries it across the asyncio task tree (and through
# asyncio.to_thread, which copies the context), the W3C-style
# ``traceparent`` header carries it across processes (the /v1 remote
# client and the /kv/parked migration wire), and stitch.py reassembles
# per-process fragments by it.
# ---------------------------------------------------------------------

trace_id_var: contextvars.ContextVar[str] = contextvars.ContextVar(
    "fasttalk_trace_id", default="")

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def propagate_enabled() -> bool:
    """TRACE_PROPAGATE gate (default on): whether outbound hops attach
    the traceparent header and inbound edges adopt it."""
    return os.getenv("TRACE_PROPAGATE", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def mint_trace_id() -> str:
    """A fresh 32-hex trace id (W3C trace-context format)."""
    return uuid.uuid4().hex


def current_trace_id() -> str:
    """The trace id bound in this context ("" when unbound)."""
    return trace_id_var.get()


def make_traceparent(trace_id: str) -> str:
    """Render a W3C ``traceparent`` header value for this hop. The
    span-id segment is minted per call (each hop is its own parent);
    we only consume the trace-id segment on the receiving side."""
    return f"00-{trace_id}-{uuid.uuid4().hex[:16]}-01"


def parse_traceparent(header: str | None) -> str | None:
    """Extract the trace id from a ``traceparent`` header value; None
    when absent or malformed (a bad header never fails the request —
    the trace just starts fresh on this process)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    tid = m.group(1)
    return None if tid == "0" * 32 else tid


def current_traceparent() -> str | None:
    """A ready-to-send traceparent header for the bound trace, or None
    when no trace is bound or propagation is disabled."""
    tid = trace_id_var.get()
    if not tid or not propagate_enabled():
        return None
    return make_traceparent(tid)


@dataclass
class Span:
    name: str
    t0: float            # time.monotonic() at start
    t1: float            # time.monotonic() at end
    attrs: dict[str, Any] = field(default_factory=dict)

    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1000.0


@dataclass
class RequestTrace:
    request_id: str
    session_id: str
    started_mono: float = field(default_factory=time.monotonic)
    spans: list[Span] = field(default_factory=list)
    phase: str = "queued"
    finished: bool = False
    dropped_spans: int = 0
    attrs: dict[str, Any] = field(default_factory=dict)
    # Fleet-wide identity: the edge-minted trace id this request's
    # spans belong to. Fragments of one logical request on different
    # processes (failover, migration) share it; stitch.py joins on it.
    trace_id: str = ""

    def age_s(self) -> float:
        return time.monotonic() - self.started_mono


@dataclass
class StepRecord:
    """One retired engine decode call: process-level telemetry that is
    not owned by any single request (a call advances every active
    slot)."""
    name: str
    t0: float
    t1: float
    attrs: dict[str, Any] = field(default_factory=dict)


class Tracer:
    """Process-wide request tracer with a bounded completed-trace ring."""

    def __init__(self, enabled: bool | None = None, ring_size: int = 256,
                 step_ring_size: int = 1024):
        if enabled is None:
            enabled = os.getenv("TRACE_ENABLED", "1").strip().lower() \
                not in ("0", "false", "off", "no")
        self.enabled = enabled
        self._lock = threading.Lock()
        self._inflight: dict[str, RequestTrace] = {}
        self._ring: deque[RequestTrace] = deque(maxlen=max(1, ring_size))
        self._steps: deque[StepRecord] = deque(maxlen=max(1, step_ring_size))
        # One anchor pair for the whole process: exporters turn any
        # monotonic timestamp into wall time with wall0 + (t - mono0).
        self.wall0 = time.time()
        self.mono0 = time.monotonic()

    # ---------------- request lifecycle ----------------

    def start(self, request_id: str, session_id: str = "",
              trace_id: str | None = None) -> bool:
        """Register an in-flight request. Returns True if this call
        created the trace (the creator is responsible for finish());
        False if it already existed or tracing is disabled.

        ``trace_id`` resolution: an explicit id wins (the serving edge
        mints one), else the id bound in the current context (a replica
        adopting a propagated traceparent), else a fresh mint — every
        trace carries a fleet-unique id either way."""
        if not self.enabled:
            return False
        tid = trace_id or trace_id_var.get() or mint_trace_id()
        with self._lock:
            existing = self._inflight.get(request_id)
            if existing is not None:
                if not existing.trace_id:
                    existing.trace_id = tid
                return False
            self._inflight[request_id] = RequestTrace(
                request_id=request_id, session_id=session_id,
                trace_id=tid)
            return True

    def finish(self, request_id: str) -> None:
        """Move a request's trace to the completed ring (idempotent)."""
        if not self.enabled:
            return
        with self._lock:
            trace = self._inflight.pop(request_id, None)
            if trace is None:
                return
            trace.finished = True
            trace.phase = "done"
            self._ring.append(trace)

    def add_span(self, request_id: str, name: str, t0: float, t1: float,
                 summary: bool = False, **attrs: Any) -> None:
        """Record a completed span with explicit monotonic timestamps
        (the engine thread records phases retroactively at
        transitions).

        ``summary=True`` marks the once-per-request phase spans
        (decode, detokenize, upstream_stream): they bypass the span
        cap, so a long generation that filled the trace with per-call
        decode_step / per-frame ws_send spans still gets its phase
        breakdown — exactly the requests the cap would otherwise
        silence. Bounded regardless: a request emits only a handful of
        summary spans by construction."""
        if not self.enabled:
            return
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is None:
                return
            if not summary and len(trace.spans) >= _MAX_SPANS_PER_TRACE:
                trace.dropped_spans += 1
                return
            trace.spans.append(Span(name, t0, t1, attrs))

    @contextmanager
    def span(self, request_id: str, name: str,
             **attrs: Any) -> Iterator[None]:
        """Context-manager form of add_span for async-side callers."""
        if not self.enabled:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(request_id, name, t0, time.monotonic(), **attrs)

    def event(self, request_id: str, name: str, **attrs: Any) -> None:
        """Zero-duration marker (e.g. first_token)."""
        now = time.monotonic()
        self.add_span(request_id, name, now, now, **attrs)

    def set_phase(self, request_id: str, phase: str, **attrs: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is not None:
                trace.phase = phase
                if attrs:
                    trace.attrs.update(attrs)

    # ---------------- engine-step telemetry ----------------

    def step(self, name: str, t0: float, t1: float, **attrs: Any) -> None:
        """Record one retired engine decode call (process-level row in
        the export, separate from any request's trace)."""
        if not self.enabled:
            return
        with self._lock:
            self._steps.append(StepRecord(name, t0, t1, attrs))

    # ---------------- read side ----------------

    def inflight_summary(self) -> list[dict[str, Any]]:
        """Live requests with current phase and age — /debug/requests."""
        with self._lock:
            traces = list(self._inflight.values())
        return [{
            "request_id": t.request_id,
            "session_id": t.session_id,
            "phase": t.phase,
            "age_s": round(t.age_s(), 3),
            "spans": len(t.spans),
            **({"attrs": dict(t.attrs)} if t.attrs else {}),
        } for t in traces]

    def get(self, request_id: str) -> RequestTrace | None:
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is not None:
                return trace
            for t in self._ring:
                if t.request_id == request_id:
                    return t
        return None

    def find_by_trace_id(self, trace_id: str) -> list[RequestTrace]:
        """Every local trace (in-flight or completed) carrying this
        fleet trace id — a failed-over request leaves one fragment per
        re-dispatch on a remote replica; stitch.py merges them."""
        if not trace_id:
            return []
        with self._lock:
            out = [t for t in self._inflight.values()
                   if t.trace_id == trace_id]
            out.extend(t for t in self._ring if t.trace_id == trace_id)
        return out

    def completed(self) -> list[RequestTrace]:
        with self._lock:
            return list(self._ring)

    def steps(self) -> list[StepRecord]:
        with self._lock:
            return list(self._steps)

    def to_wall(self, mono_t: float) -> float:
        """Monotonic timestamp → wall-clock epoch seconds."""
        return self.wall0 + (mono_t - self.mono0)

    def clear(self) -> None:
        """Drop all recorded state (in-flight, ring, steps)."""
        with self._lock:
            self._inflight.clear()
            self._ring.clear()
            self._steps.clear()

    def scoped(self, component: str) -> "ComponentTracer":
        """A view of this tracer that stamps ``component=<name>`` on
        every span/event/step it records — how router, serving and
        each in-proc replica distinguish their rows inside the ONE
        shared trace of a BENCH_MODE=fleet process."""
        return ComponentTracer(self, component)


class ComponentTracer:
    """Thin delegating wrapper around a Tracer that injects a
    ``component`` attr into recorded spans, events and step records
    (explicit attrs win). Lifecycle methods (start/finish/set_phase/
    read side) pass straight through — there is still exactly one
    underlying tracer and one trace per request id."""

    def __init__(self, inner: Tracer, component: str):
        self._inner = inner
        self.component = component

    @property
    def enabled(self) -> bool:
        return self._inner.enabled

    def add_span(self, request_id: str, name: str, t0: float, t1: float,
                 summary: bool = False, **attrs: Any) -> None:
        attrs.setdefault("component", self.component)
        self._inner.add_span(request_id, name, t0, t1, summary=summary,
                             **attrs)

    def event(self, request_id: str, name: str, **attrs: Any) -> None:
        attrs.setdefault("component", self.component)
        self._inner.event(request_id, name, **attrs)

    @contextmanager
    def span(self, request_id: str, name: str,
             **attrs: Any) -> Iterator[None]:
        if not self._inner.enabled:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(request_id, name, t0, time.monotonic(),
                          **attrs)

    def step(self, name: str, t0: float, t1: float,
             **attrs: Any) -> None:
        attrs.setdefault("component", self.component)
        self._inner.step(name, t0, t1, **attrs)

    def scoped(self, component: str) -> "ComponentTracer":
        return ComponentTracer(self._inner, component)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


@contextmanager
def bind_request(request_id: str,
                 trace_id: str | None = None) -> Iterator[None]:
    """Bind the request id into the logging/tracing ContextVar so every
    log line inside the block carries it (utils/logger formatters read
    the same var). When ``trace_id`` is given, bind it too — downstream
    hops in this task tree (the /v1 remote client, to_thread migration
    workers) read it via current_traceparent()."""
    token = request_id_var.set(request_id)
    t_token = trace_id_var.set(trace_id) if trace_id else None
    try:
        yield
    finally:
        request_id_var.reset(token)
        if t_token is not None:
            trace_id_var.reset(t_token)


_tracer: Tracer | None = None


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        _tracer = Tracer()
    return _tracer


def reset_tracer() -> None:
    """Test hook: clear the process-wide tracer IN PLACE — modules
    cache the Tracer at construction time (engine.__init__), so
    dropping the singleton would orphan their handle exactly the way
    reset_metrics() used to orphan cached counters."""
    if _tracer is not None:
        _tracer.clear()
