"""Trace exporters: Chrome trace-event JSON (Perfetto) and JSONL.

Chrome trace-event format (the subset Perfetto and chrome://tracing
load): a top-level ``{"traceEvents": [...]}`` object whose events are
complete spans (``"ph": "X"``) with microsecond ``ts``/``dur``, plus
``"M"`` metadata events naming each row. One pid for the process; one
tid per request (rows sort by first span), tid 0 reserved for the
engine-step telemetry row.

JSONL: one flat object per span — the offline-analysis format
scripts/trace_report.py consumes. Schema per line:

    {"request_id": str|null, "session_id": str, "span": str,
     "ts": epoch-seconds float, "dur_ms": float, "attrs": {...}}

Engine-step records export with ``request_id: null`` and the span name
``engine_step`` so per-request phases and process-level call telemetry
never mix in percentile tables.
"""

from __future__ import annotations

import json
from typing import Any, Iterable, TextIO

from fasttalk_tpu.observability.trace import (RequestTrace, StepRecord,
                                              Tracer)

_ENGINE_TID = 0


def chrome_trace(tracer: Tracer, traces: Iterable[RequestTrace],
                 steps: Iterable[StepRecord] = ()) -> dict[str, Any]:
    """Render traces (+ optional engine-step records) as a Chrome
    trace-event JSON object loadable in Perfetto."""
    events: list[dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
        "args": {"name": "fasttalk-tpu"},
    }, {
        "name": "thread_name", "ph": "M", "pid": 1, "tid": _ENGINE_TID,
        "args": {"name": "engine steps"},
    }]
    for tid, trace in enumerate(traces, start=1):
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": f"req {trace.request_id}"},
        })
        for span in trace.spans:
            events.append({
                "name": span.name,
                "ph": "X",
                "pid": 1,
                "tid": tid,
                "ts": tracer.to_wall(span.t0) * 1e6,
                "dur": max(0.0, (span.t1 - span.t0) * 1e6),
                "args": {"request_id": trace.request_id,
                         "session_id": trace.session_id, **span.attrs},
            })
        if trace.dropped_spans:
            events.append({
                "name": "spans_dropped", "ph": "I", "pid": 1, "tid": tid,
                "ts": tracer.to_wall(trace.started_mono) * 1e6, "s": "t",
                "args": {"dropped": trace.dropped_spans},
            })
    for rec in steps:
        events.append({
            "name": rec.name,
            "ph": "X",
            "pid": 1,
            "tid": _ENGINE_TID,
            "ts": tracer.to_wall(rec.t0) * 1e6,
            "dur": max(0.0, (rec.t1 - rec.t0) * 1e6),
            "args": dict(rec.attrs),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def jsonl_dump(tracer: Tracer, traces: Iterable[RequestTrace],
               steps: Iterable[StepRecord] = ()) -> str:
    """Render traces as JSONL (one span per line; trailing newline)."""
    lines: list[str] = []
    for trace in traces:
        for span in trace.spans:
            lines.append(json.dumps({
                "request_id": trace.request_id,
                "session_id": trace.session_id,
                "span": span.name,
                "ts": tracer.to_wall(span.t0),
                "dur_ms": span.dur_ms,
                "attrs": span.attrs,
            }, ensure_ascii=False, default=str))
    for rec in steps:
        lines.append(json.dumps({
            "request_id": None,
            "session_id": "",
            "span": rec.name,
            "ts": tracer.to_wall(rec.t0),
            "dur_ms": (rec.t1 - rec.t0) * 1000.0,
            "attrs": rec.attrs,
        }, ensure_ascii=False, default=str))
    return "\n".join(lines) + ("\n" if lines else "")


def load_jsonl(fp: TextIO) -> list[dict[str, Any]]:
    """Parse a JSONL trace dump, skipping blank lines; raises ValueError
    naming the offending line number on malformed input."""
    records: list[dict[str, Any]] = []
    for i, line in enumerate(fp, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"line {i}: not valid JSON ({e})") from e
        if not isinstance(obj, dict) or "span" not in obj:
            raise ValueError(f"line {i}: not a span record")
        records.append(obj)
    return records
