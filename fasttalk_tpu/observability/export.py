"""Trace exporters: Chrome trace-event JSON (Perfetto) and JSONL.

Chrome trace-event format (the subset Perfetto and chrome://tracing
load): a top-level ``{"traceEvents": [...]}`` object whose events are
complete spans (``"ph": "X"``) with microsecond ``ts``/``dur``, plus
``"M"`` metadata events naming each row. One pid for the process; one
tid per request (rows sort by first span), tid 0 reserved for the
engine-step telemetry row.

JSONL: one flat object per span — the offline-analysis format
scripts/trace_report.py consumes. Schema per line:

    {"request_id": str|null, "session_id": str, "span": str,
     "ts": epoch-seconds float, "dur_ms": float, "attrs": {...}}

Engine-step records export with ``request_id: null`` and the span name
``engine_step`` so per-request phases and process-level call telemetry
never mix in percentile tables.
"""

from __future__ import annotations

import json
import re
from typing import Any, Iterable, TextIO

from fasttalk_tpu.observability.trace import (RequestTrace, StepRecord,
                                              Tracer)

_ENGINE_TID = 0


def chrome_trace(tracer: Tracer, traces: Iterable[RequestTrace],
                 steps: Iterable[StepRecord] = ()) -> dict[str, Any]:
    """Render traces (+ optional engine-step records) as a Chrome
    trace-event JSON object loadable in Perfetto."""
    events: list[dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
        "args": {"name": "fasttalk-tpu"},
    }, {
        "name": "thread_name", "ph": "M", "pid": 1, "tid": _ENGINE_TID,
        "args": {"name": "engine steps"},
    }]
    for tid, trace in enumerate(traces, start=1):
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": f"req {trace.request_id}"},
        })
        for span in trace.spans:
            events.append({
                "name": span.name,
                "ph": "X",
                "pid": 1,
                "tid": tid,
                "ts": tracer.to_wall(span.t0) * 1e6,
                "dur": max(0.0, (span.t1 - span.t0) * 1e6),
                "args": {"request_id": trace.request_id,
                         "session_id": trace.session_id, **span.attrs},
            })
        if trace.dropped_spans:
            events.append({
                "name": "spans_dropped", "ph": "I", "pid": 1, "tid": tid,
                "ts": tracer.to_wall(trace.started_mono) * 1e6, "s": "t",
                "args": {"dropped": trace.dropped_spans},
            })
    for rec in steps:
        events.append({
            "name": rec.name,
            "ph": "X",
            "pid": 1,
            "tid": _ENGINE_TID,
            "ts": tracer.to_wall(rec.t0) * 1e6,
            "dur": max(0.0, (rec.t1 - rec.t0) * 1e6),
            "args": dict(rec.attrs),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def jsonl_dump(tracer: Tracer, traces: Iterable[RequestTrace],
               steps: Iterable[StepRecord] = ()) -> str:
    """Render traces as JSONL (one span per line; trailing newline)."""
    lines: list[str] = []
    for trace in traces:
        for span in trace.spans:
            lines.append(json.dumps({
                "request_id": trace.request_id,
                "session_id": trace.session_id,
                "span": span.name,
                "ts": tracer.to_wall(span.t0),
                "dur_ms": span.dur_ms,
                "attrs": span.attrs,
            }, ensure_ascii=False, default=str))
    for rec in steps:
        lines.append(json.dumps({
            "request_id": None,
            "session_id": "",
            "span": rec.name,
            "ts": tracer.to_wall(rec.t0),
            "dur_ms": (rec.t1 - rec.t0) * 1000.0,
            "attrs": rec.attrs,
        }, ensure_ascii=False, default=str))
    return "\n".join(lines) + ("\n" if lines else "")


_PROM_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"     # metric name
    r"(?:\{(.*)\})?"                   # optional label block
    r" (\S+)"                          # value
    r"(?: ([0-9-]+))?$")               # optional timestamp (dropped)
_PROM_SUFFIXES = ("_bucket", "_sum", "_count")


def _prom_family(name: str) -> str:
    for suffix in _PROM_SUFFIXES:
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def _prom_parse(text: str) -> dict[str, dict[str, Any]]:
    """Exposition text -> family -> {help, type, samples:[(name,
    labels_str, value_str)]}, in family order. Free comments and
    malformed lines are dropped (the merged output is re-validated by
    check_prometheus in tests; a broken replica must not break the
    fleet view)."""
    families: dict[str, dict[str, Any]] = {}

    def fam(name: str) -> dict[str, Any]:
        f = families.get(name)
        if f is None:
            f = {"help": None, "type": None, "samples": []}
            families[name] = f
        return f

    for line in text.splitlines():
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                key = "help" if parts[1] == "HELP" else "type"
                f = fam(parts[2])
                if f[key] is None:
                    f[key] = parts[3] if len(parts) > 3 else ""
            continue
        m = _PROM_SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, value, _ts = m.groups()
        family = _prom_family(name)
        if family not in families and name in families:
            family = name
        fam(family)["samples"].append((name, labels or "", value))
    return families


def merge_prometheus(local_text: str, local_replica: str,
                     remotes: dict[str, str | None]) -> str:
    """Label-merged fleet exposition for ``GET /fleet/metrics``.

    Counters and gauges get a ``replica="<id>"`` label per source;
    histograms are SUMMED across replicas instead (the strict
    validator — and a sane scraper — requires one monotone bucket
    ladder per family, and every replica runs the same bucket
    bounds). The router-front process (router + any in-proc replicas,
    which share one registry) contributes as ``local_replica``;
    unreachable remotes (value None) are noted as free comments so an
    operator sees the gap instead of inferring it from absent
    series."""
    sources: list[tuple[str, dict[str, dict[str, Any]]]] = [
        (local_replica, _prom_parse(local_text))]
    unreachable: list[str] = []
    for rid in sorted(remotes):
        text = remotes[rid]
        if text is None:
            unreachable.append(rid)
        else:
            sources.append((rid, _prom_parse(text)))

    # Family order: local first, then any remote-only families.
    order: list[str] = []
    merged: dict[str, dict[str, Any]] = {}
    for rid, families in sources:
        for name, f in families.items():
            if name not in merged:
                order.append(name)
                merged[name] = {"help": f["help"], "type": f["type"],
                                "per_replica": []}
            m = merged[name]
            if m["help"] is None:
                m["help"] = f["help"]
            if m["type"] is None:
                m["type"] = f["type"]
            m["per_replica"].append((rid, f["samples"]))

    def labelled(labels: str, rid: str) -> str:
        extra = f'replica="{rid}"'
        return f"{labels},{extra}" if labels else extra

    lines: list[str] = []
    for rid in unreachable:
        lines.append(f"# replica {rid} unreachable at scrape time")
    for name in order:
        m = merged[name]
        if m["help"] is not None:
            lines.append(f"# HELP {name} {m['help']}")
        if m["type"] is not None:
            lines.append(f"# TYPE {name} {m['type']}")
        if m["type"] == "histogram":
            # Sum bucket counts / _sum / _count by le across replicas.
            buckets: dict[str, float] = {}
            le_order: list[str] = []
            total_sum = 0.0
            total_count = 0.0
            for rid, samples in m["per_replica"]:
                for sname, labels, value in samples:
                    try:
                        v = float(value)
                    except ValueError:
                        continue
                    if sname.endswith("_bucket"):
                        lem = re.search(r'le="((?:[^"\\]|\\.)*)"',
                                        labels)
                        if lem is None:
                            continue
                        le = lem.group(1)
                        if le not in buckets:
                            le_order.append(le)
                        buckets[le] = buckets.get(le, 0.0) + v
                    elif sname.endswith("_sum"):
                        total_sum += v
                    elif sname.endswith("_count"):
                        total_count += v
            for le in le_order:
                acc = buckets[le]
                acc_s = repr(int(acc)) if acc == int(acc) else repr(acc)
                lines.append(f'{name}_bucket{{le="{le}"}} {acc_s}')
            lines.append(f"{name}_sum {total_sum}")
            cnt = (repr(int(total_count))
                   if total_count == int(total_count)
                   else repr(total_count))
            lines.append(f"{name}_count {cnt}")
        else:
            for rid, samples in m["per_replica"]:
                for sname, labels, value in samples:
                    lines.append(
                        f"{sname}{{{labelled(labels, rid)}}} {value}")
    lines.append("")
    return "\n".join(lines)


def load_jsonl(fp: TextIO) -> list[dict[str, Any]]:
    """Parse a JSONL trace dump, skipping blank lines; raises ValueError
    naming the offending line number on malformed input."""
    records: list[dict[str, Any]] = []
    for i, line in enumerate(fp, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"line {i}: not valid JSON ({e})") from e
        if not isinstance(obj, dict) or "span" not in obj:
            raise ValueError(f"line {i}: not a span record")
        records.append(obj)
    return records
