"""Bounded structured event log: the service's operational incident record.

Metrics say *how much*, traces say *where one request went* — neither
says *what happened to the service*: when shedding started, when the
watchdog caught a hung engine step, when a drain began, when a
mid-traffic recompile stalled the pipeline. This module is that third
surface: a process-wide bounded ring of typed events, exposed as
``GET /events`` on the monitoring port and optionally mirrored to a
JSONL file for log shippers (``EVENTS_JSONL`` env).

Event kinds in use across the stack (open set — callers may add more):

- ``slo_burn_start`` / ``slo_burn_stop`` — a priority class entered /
  left an SLO burn-rate alert state (observability/slo.py)
- ``stall_detected`` / ``stall_cleared`` — the watchdog caught (or saw
  recover) a hung engine step or a token-stalled request
  (observability/watchdog.py)
- ``watchdog_cancel`` — a hopelessly stalled request was terminated
  with a proper terminal error instead of a silent WebSocket
- ``shed_burst`` — admission control started shedding (coalesced: one
  event per burst with a running ``count``, not one per shed)
- ``drain`` — graceful drain began (server shutdown)
- ``recompile`` — a jitted executable was compiled while serving
  traffic (warmup misses; a mid-stream compile is a latency incident)
- ``engine_restart`` — supervised in-process engine recovery ran
- ``loop_lag`` — the serving event loop fell badly behind

Design constraints mirror the tracer's: cheap (one lock + one deque
append), thread-safe (events arrive from the engine thread, the asyncio
loop and the scheduler's callers), bounded (ring of ``EVENTS_RING``
entries, default 512), and clearable in place for tests.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from fasttalk_tpu.utils.logger import get_logger

log = get_logger("observability.events")

_SEVERITIES = ("info", "warning", "critical")


def env_float(name: str, default: float) -> float:
    """Silent-fallback float env knob (shared by the observability
    modules; utils.config keeps its stricter raising variant for the
    validated Config surface)."""
    raw = os.getenv(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass
class Event:
    seq: int                 # monotonically increasing per process
    kind: str
    severity: str
    ts: float                # wall-clock epoch seconds (first emission)
    last_ts: float           # wall clock of the latest coalesced hit
    count: int = 1           # coalesced occurrences
    attrs: dict[str, Any] = field(default_factory=dict)
    last_mirrored: float = 0.0  # JSONL-mirror throttle (not exported)
    ckey: str = ""              # coalesce key (not exported)

    def to_dict(self) -> dict[str, Any]:
        out = {
            "seq": self.seq,
            "kind": self.kind,
            "severity": self.severity,
            "ts": self.ts,
            "count": self.count,
        }
        if self.count > 1:
            out["last_ts"] = self.last_ts
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class EventLog:
    """Process-wide bounded ring of typed operational events."""

    def __init__(self, ring_size: int | None = None,
                 jsonl_path: str | None = None,
                 clock=time.time):
        if ring_size is None:
            try:
                ring_size = int(os.getenv("EVENTS_RING", "512"))
            except ValueError:
                ring_size = 512
        if jsonl_path is None:
            jsonl_path = os.getenv("EVENTS_JSONL", "")
        self.ring_size = max(1, ring_size)
        self.jsonl_path = jsonl_path or ""
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque[Event] = deque()
        self._seq = 0
        self._total = 0
        # coalesce key -> most recent event still in the ring
        # (coalescing handle; O(1) instead of scanning the ring).
        self._last_by_key: dict[tuple[str, str], Event] = {}
        self._jsonl_warned = False
        # Subscribers (observability/flight.py): called outside the
        # lock on every emit (including coalesce bumps). A listener
        # must be cheap or hand off to its own thread — it runs on the
        # EMITTER's thread (engine, asyncio loop, scheduler callers).
        self._listeners: list[Any] = []

    def add_listener(self, fn) -> None:
        """Subscribe ``fn(event)`` to every emit (idempotent)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def emit(self, kind: str, severity: str = "info",
             coalesce_s: float = 0.0, coalesce_key: str = "",
             **attrs: Any) -> Event:
        """Record one event. With ``coalesce_s`` > 0, a repeat of the
        same kind (and ``coalesce_key`` — e.g. the shed *reason*, so
        queue_full and slo_burn bursts stay distinct events) within
        that window bumps the previous event's ``count`` and refreshes
        its attrs instead of appending — burst kinds like
        ``shed_burst`` must not flood the ring out of its useful
        history. The JSONL mirror re-writes a bumped event at most
        once per window, so the shipped log still ends up carrying the
        burst's running count rather than a permanent ``count: 1``."""
        if severity not in _SEVERITIES:
            severity = "info"
        now = self._clock()
        key = (kind, coalesce_key)
        mirror_ev: Event | None = None
        with self._lock:
            if coalesce_s > 0:
                last = self._last_by_key.get(key)
                if last is not None and now - last.last_ts <= coalesce_s:
                    last.count += 1
                    last.last_ts = now
                    last.attrs.update(attrs)  # freshest depth/retry/...
                    self._total += 1
                    if now - last.last_mirrored >= coalesce_s:
                        last.last_mirrored = now
                        mirror_ev = last
                    ev = last
                else:
                    ev = None
            else:
                ev = None
            if ev is None:
                self._seq += 1
                self._total += 1
                ev = Event(seq=self._seq, kind=kind, severity=severity,
                           ts=now, last_ts=now, last_mirrored=now,
                           attrs=dict(attrs), ckey=coalesce_key)
                self._ring.append(ev)
                self._last_by_key[key] = ev
                mirror_ev = ev
                if len(self._ring) > self.ring_size:
                    dropped = self._ring.popleft()  # O(1) eviction
                    dkey = (dropped.kind, dropped.ckey)
                    if self._last_by_key.get(dkey) is dropped:
                        self._last_by_key.pop(dkey, None)
        # Mirror outside the lock: a slow disk must not serialise the
        # engine thread against the asyncio loop on the event lock.
        if self.jsonl_path and mirror_ev is not None:
            self._mirror(mirror_ev)
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(ev)
            except Exception as e:  # a broken listener must not take
                log.error(f"event listener failed: {e}")  # emit() down
        return ev

    def _mirror(self, ev: Event) -> None:
        try:
            with open(self.jsonl_path, "a", encoding="utf-8") as fp:
                fp.write(json.dumps(ev.to_dict(), ensure_ascii=False,
                                    default=str) + "\n")
        except OSError as e:
            if not self._jsonl_warned:
                self._jsonl_warned = True
                log.warning(f"events JSONL mirror disabled: {e}")
            self.jsonl_path = ""

    def recent(self, limit: int = 100,
               kind: str | None = None,
               min_severity: str | None = None) -> list[dict[str, Any]]:
        """Newest-first event dicts, optionally filtered."""
        with self._lock:
            events = list(self._ring)
        events.reverse()
        if kind:
            events = [e for e in events if e.kind == kind]
        if min_severity in _SEVERITIES:
            floor = _SEVERITIES.index(min_severity)
            events = [e for e in events
                      if _SEVERITIES.index(e.severity) >= floor]
        return [e.to_dict() for e in events[:max(0, limit)]]

    @property
    def total_emitted(self) -> int:
        return self._total

    def clear(self) -> None:
        """Test hook: drop all recorded events IN PLACE (modules cache
        the EventLog handle at construction, like metrics/tracer)."""
        with self._lock:
            self._ring.clear()
            self._last_by_key.clear()
            self._total = 0


_events: EventLog | None = None


def get_events() -> EventLog:
    global _events
    if _events is None:
        _events = EventLog()
    return _events


def reset_events() -> None:
    """Test hook: clear the process-wide event log in place."""
    if _events is not None:
        _events.clear()
