"""Continuous host profiler: always-on stack sampling by thread role.

The perf ledger (observability/perf.py) decomposes engine wall time
into device-busy intervals and host gaps, but a gap is just an absence
— nothing in the step records says WHAT the host was doing while the
device starved. This module closes that hole from the outside: a
daemon thread samples ``sys._current_frames()`` at ``PROF_HZ`` (default
~67 Hz — deliberately co-prime with common 10/20/50 ms periodic work so
the sampler doesn't alias against it), aggregates collapsed stacks per
thread ROLE (engine loop, KV copy thread, asyncio event loop, SPMD
broadcaster), and keeps a bounded timeline of what the engine thread
was doing at each sample so the ledger can classify its host gaps by
cause (detok / ws_send / scheduler / radix / gc / other).

Overhead contract (same discipline as resilience/failpoints.py): the
profiler is strictly PULL-based — no hot path ever calls into it, the
engine/serving threads carry zero added instructions, and with
``PROF_ENABLED=false`` no thread exists at all. The only cost when on
is the sampler thread's own work (~15 ms-spaced GIL grabs of a few
hundred microseconds); ``BENCH_MODE=profiler`` measures the on/off
throughput delta and gates it at <= 1%.

GC pauses are invisible to stack sampling (the collector runs inside
whatever frame triggered allocation), so those are captured exactly
instead: a ``gc.callbacks`` hook records each collection's
[start, stop] interval, and the ledger subtracts the overlap from its
host gaps before distributing the rest across sampled causes.

Read side:

- ``GET /debug/profile`` — flamegraph-collapsed text (one
  ``role;frame;frame... count`` line per aggregated stack, feed it
  straight to ``flamegraph.pl`` / speedscope), ``?format=json`` for
  the structured report.
- flight bundles (local and fleet) fold ``profile.txt`` +
  ``profile.json`` sections in, so every incident ships with "what was
  every thread doing".
- ``causes_between(t0, t1)`` / ``gc_overlap_s(t0, t1)`` — the perf
  ledger's host-gap classification inputs (time.monotonic clock, same
  as the tracer's step records).
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
from collections import deque
from typing import Any

from fasttalk_tpu.utils.logger import get_logger

log = get_logger("observability.profiler")

DEFAULT_HZ = 67.0
DEFAULT_MAX_STACKS = 2000
_MAX_DEPTH = 48           # frames kept per stack (root-first)
_TIMELINE_CAP = 8192      # engine-thread cause observations kept
_GC_CAP = 512             # completed GC pause intervals kept

# Thread-name prefix -> role. Names are set at thread creation
# (engine loop: engine.py start(); KV copy: kvcache/offload.py; SPMD:
# spmd/broadcast.py); MainThread runs the asyncio event loop under
# the serving entrypoint.
_ROLES: tuple[tuple[str, str], ...] = (
    ("tpu-engine", "engine_loop"),
    ("kv-offload", "kv_copy"),
    ("MainThread", "event_loop"),
    ("spmd-", "spmd"),
)

# Host-gap cause taxonomy (ROADMAP item 4's input): substrings matched
# against "filename:function" of every frame in an engine-thread
# sample, most-specific first. A sample names ONE cause — the deepest
# match wins because the leaf frames say what the loop iteration was
# actually doing while the outer frames are always the engine loop.
_CAUSES: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("detok", ("detok", "tokenizer", "_consume_token", "_flush_emit",
               "decode_text")),
    ("ws_send", ("websocket", "ws_server", "_emit", "send_json",
                 "send_str", "connection_manager")),
    ("radix", ("radix", "blocks.py", "_kv_blocks", "_paged",
               "allocator", "alias")),
    ("scheduler", ("_admit", "_schedule", "scheduler", "_sched",
                   "submit", "queue_wait", "_try_restore",
                   "_park_slot")),
)
CAUSE_NAMES = ("detok", "ws_send", "scheduler", "radix", "gc", "other")


def _env_bool(name: str, default: bool) -> bool:
    raw = os.getenv(name, "").strip().lower()
    if not raw:
        return default
    return raw in ("1", "true", "yes", "on")


def _env_float(name: str, default: float) -> float:
    raw = os.getenv(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class ContinuousProfiler:
    """The process's stack sampler; standalone-constructible in tests
    (injectable clock for the read side, ``sample_once()`` to drive
    sampling deterministically without the thread)."""

    def __init__(self, *, enabled: bool | None = None,
                 hz: float | None = None,
                 max_stacks: int | None = None,
                 clock=time.monotonic):
        self.enabled = _env_bool("PROF_ENABLED", True) \
            if enabled is None else enabled
        self.hz = _env_float("PROF_HZ", DEFAULT_HZ) if hz is None else hz
        self.hz = min(1000.0, max(0.1, self.hz))
        self.max_stacks = int(_env_float("PROF_MAX_STACKS",
                                         DEFAULT_MAX_STACKS)) \
            if max_stacks is None else max_stacks
        self._clock = clock
        self._lock = threading.Lock()
        # role -> {collapsed_stack: count}; bounded at max_stacks
        # DISTINCT stacks across all roles (each is ~a few hundred
        # bytes; the counter grows unbounded, the key set must not).
        self._stacks: dict[str, dict[str, int]] = {}
        self._role_samples: dict[str, int] = {}
        self._timeline: deque[tuple[float, str]] = deque(
            maxlen=_TIMELINE_CAP)
        self._gc_done: deque[tuple[float, float]] = deque(maxlen=_GC_CAP)
        self._gc_t0: float | None = None
        self._gc_pauses = 0
        self._gc_pause_s = 0.0
        self.samples = 0
        self.errors = 0
        self.dropped_stacks = 0
        self.started_at: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._gc_installed = False

    # ---------------- lifecycle ----------------

    def start(self) -> None:
        """Spawn the sampler thread (idempotent; a disabled profiler
        spawns nothing — the off state owns no resources at all)."""
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self.started_at = self._clock()
        if not self._gc_installed:
            gc.callbacks.append(self._on_gc)
            self._gc_installed = True
        self._thread = threading.Thread(target=self._run,
                                        name="prof-sampler", daemon=True)
        self._thread.start()
        log.info(f"continuous profiler sampling at {self.hz:g} Hz "
                 f"(max {self.max_stacks} stacks)")

    def stop(self) -> None:
        t = self._thread
        self._stop.set()
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        if self._gc_installed:
            try:
                gc.callbacks.remove(self._on_gc)
            except ValueError:
                pass
            self._gc_installed = False

    # ---------------- sampling ----------------

    def _run(self) -> None:
        period = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(period):
            try:
                self.sample_once(exclude=me)
            except Exception as e:
                # A torn frame mid-walk (threads die under us — that's
                # the point of sampling live threads) costs one tick,
                # never the sampler.
                self.errors += 1
                if self.errors <= 3:
                    log.debug(f"profile sample failed: {e}")

    def sample_once(self, exclude: int | None = None) -> None:
        """One sampling tick: snapshot every thread's stack, aggregate
        per role, and note the engine thread's cause observation."""
        now = self._clock()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        with self._lock:
            self.samples += 1
            for tid, frame in frames.items():
                if tid == exclude:
                    continue
                role = self._role(names.get(tid, f"tid-{tid}"))
                parts: list[str] = []
                cause: str | None = None
                f = frame
                depth = 0
                while f is not None and depth < _MAX_DEPTH:
                    code = f.f_code
                    parts.append(code.co_name)
                    if role == "engine_loop" and cause is None:
                        # leaf-first walk: the first (deepest) match
                        # names the cause
                        cause = self._classify(code.co_filename,
                                               code.co_name)
                    f = f.f_back
                    depth += 1
                parts.reverse()  # root-first, the collapsed convention
                stack = ";".join(parts)
                per_role = self._stacks.setdefault(role, {})
                self._role_samples[role] = \
                    self._role_samples.get(role, 0) + 1
                if stack in per_role:
                    per_role[stack] += 1
                elif sum(len(d) for d in self._stacks.values()) \
                        < self.max_stacks:
                    per_role[stack] = 1
                else:
                    self.dropped_stacks += 1
                if role == "engine_loop":
                    self._timeline.append((now, cause or "other"))

    @staticmethod
    def _role(name: str) -> str:
        for prefix, role in _ROLES:
            if name.startswith(prefix):
                return role
        return name

    @staticmethod
    def _classify(filename: str, func: str) -> str | None:
        probe = f"{filename.rsplit('/', 1)[-1]}:{func}"
        for cause, needles in _CAUSES:
            for n in needles:
                if n in probe:
                    return cause
        return None

    # ---------------- GC pause capture ----------------

    def _on_gc(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_t0 = self._clock()
        elif phase == "stop" and self._gc_t0 is not None:
            t0, self._gc_t0 = self._gc_t0, None
            t1 = self._clock()
            self._gc_pauses += 1
            self._gc_pause_s += t1 - t0
            self._gc_done.append((t0, t1))

    # ---------------- ledger read side ----------------

    def causes_between(self, t0: float, t1: float) -> dict[str, int]:
        """Engine-thread cause observation counts within [t0, t1]
        (monotonic clock — the tracer's). Empty dict = the sampler saw
        nothing there (off, or the gap was shorter than a tick)."""
        out: dict[str, int] = {}
        with self._lock:
            snap = list(self._timeline)
        for t, cause in snap:
            if t0 <= t <= t1:
                out[cause] = out.get(cause, 0) + 1
        return out

    def gc_overlap_s(self, t0: float, t1: float) -> float:
        """Seconds of captured GC pause overlapping [t0, t1]."""
        with self._lock:
            snap = list(self._gc_done)
        total = 0.0
        for g0, g1 in snap:
            lo, hi = max(t0, g0), min(t1, g1)
            if hi > lo:
                total += hi - lo
        return total

    # ---------------- report side ----------------

    def collapsed(self) -> str:
        """Flamegraph-collapsed text: ``role;frame;... count`` lines,
        hottest first."""
        with self._lock:
            rows = [(f"{role};{stack}", n)
                    for role, stacks in self._stacks.items()
                    for stack, n in stacks.items()]
        rows.sort(key=lambda r: (-r[1], r[0]))
        return "\n".join(f"{stack} {n}" for stack, n in rows) + "\n"

    def report(self, top: int = 20) -> dict[str, Any]:
        with self._lock:
            threads = {}
            for role, stacks in self._stacks.items():
                hot = sorted(stacks.items(), key=lambda kv: -kv[1])[:top]
                threads[role] = {
                    "samples": self._role_samples.get(role, 0),
                    "distinct_stacks": len(stacks),
                    "top": [{"stack": s, "count": n} for s, n in hot],
                }
            timeline_counts: dict[str, int] = {}
            for _, cause in self._timeline:
                timeline_counts[cause] = timeline_counts.get(cause, 0) + 1
        return {
            "enabled": self.enabled,
            "running": self._thread is not None,
            "hz": self.hz,
            "samples": self.samples,
            "errors": self.errors,
            "dropped_stacks": self.dropped_stacks,
            "max_stacks": self.max_stacks,
            "started_at": self.started_at,
            "threads": threads,
            "engine_causes": timeline_counts,
            "gc": {"pauses": self._gc_pauses,
                   "pause_s": round(self._gc_pause_s, 6)},
        }

    def clear(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._role_samples.clear()
            self._timeline.clear()
            self._gc_done.clear()
            self.samples = 0
            self.errors = 0
            self.dropped_stacks = 0
            self._gc_pauses = 0
            self._gc_pause_s = 0.0


_profiler: ContinuousProfiler | None = None


def get_profiler() -> ContinuousProfiler:
    global _profiler
    if _profiler is None:
        _profiler = ContinuousProfiler()
    return _profiler


def reset_profiler() -> None:
    """Test hook: stop the sampler and drop the singleton so the next
    get_profiler() re-reads the environment."""
    global _profiler
    if _profiler is not None:
        _profiler.stop()
    _profiler = None
