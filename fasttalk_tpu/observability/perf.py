"""Performance attribution ledger: where engine wall time actually went.

PR 1 records *that* a decode call happened (the tracer's engine-step
ring) and PR 3 pages *when* latency promises break — neither explains
the gap between achieved throughput and what the hardware could do.
This module closes that gap with a rolling attribution report over the
engine's step/prefill telemetry:

- **Wall-time decomposition.** The step ring's records are intervals
  on the engine clock (dispatch → retirement for decode calls,
  dispatch for prefill calls). Their union is *device-busy* time; the
  gaps between them split into *host gap* (short — dispatch overhead,
  host-side token handling, admission work between calls) and *idle*
  (long — no work to run), by the ``PERF_IDLE_GAP_MS`` threshold
  (default 250). busy + host_gap + idle == the report window, exactly.
- **Padding waste.** Fixed shapes buy compile stability by computing
  rows that are thrown away: decode calls advance all S slots whether
  active or not (and speculative verify blocks compute draft+1
  positions of which only the accepted prefix is kept), and prefill
  pads prompts up to power-of-two buckets and group sizes. Every
  record carries the token rows it computed and the tokens that were
  actually useful; the waste fraction is 1 - useful/computed.
- **Occupancy-weighted useful-token throughput.** Useful tokens per
  wall second and per device-busy second, next to the duration-
  weighted mean batch occupancy — the number that says whether low
  tok/s is an empty batch or a slow step.
- **MFU.** Records carry a per-call FLOP estimate from the bound
  model config (2·params per token plus the attention term at the
  call's KV bucket); achieved FLOP/s over the window against the
  device's peak (detected from the device kind, overridable with
  ``PERF_PEAK_TFLOPS``) is the achieved-vs-peak roofline number the
  ROADMAP's "as fast as the hardware allows" is judged by.
- **Compile ledger.** Every ``_note_compile`` signature (warmup and
  serving-time) is counted per key, so "why did p99 spike" can be
  answered with "the 2048 prefill bucket compiled at 14:03" instead
  of a profiler session.

Exposed as ``GET /perf`` on the monitoring port, ``perf_*`` Prometheus
gauges (refreshed at scrape time), a ``--perf`` section in
``scripts/trace_report.py`` (offline, from a JSONL dump), and a
``perf`` block in bench.py's JSON output.

Same design constraints as the tracer: cheap (reads the existing ring;
recording adds one dict update per compile), thread-safe, clearable in
place for tests, fake-clock drivable (``report(now=...)``).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any

from fasttalk_tpu.observability.events import env_float
from fasttalk_tpu.utils.metrics import get_metrics

DEFAULT_WINDOW_S = 60.0
DEFAULT_IDLE_GAP_MS = 250.0

# Peak dense bf16 TFLOP/s per chip by device-kind substring (public
# spec sheets); the roofline denominator when PERF_PEAK_TFLOPS is
# unset. Unknown kinds (CPU, new chips) report mfu: null rather than a
# made-up number.
PEAK_TFLOPS_BF16 = (
    ("v6e", 918.0), ("v6", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
)

# Peak HBM bandwidth GB/s per chip by device-kind substring (public
# spec sheets) — the roofline denominator for the KV-bandwidth
# utilisation figure (PERF_PEAK_HBM_GBPS overrides). Decode is
# KV-read-bound at scale, so this sits next to MFU: a call can be far
# off the FLOP roofline while saturating HBM — which is exactly what
# the int8 KV tier (KV_QUANT, docs/KVCACHE.md) halves.
PEAK_HBM_GBPS = (
    ("v6e", 1640.0), ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0), ("v5 lite", 819.0), ("v5litepod", 819.0),
    ("v4", 1228.0),
)

# Step-ring record names this ledger aggregates (engine/engine.py):
# decode calls (dispatch → retirement), prefill calls (dispatch), and
# auxiliary device programs (park/restore slices, block copies,
# structured sample-and-place) — the _OP records carry no token
# statistics, only device-busy intervals and a program key.
_STEP = "engine_step"
_PREFILL = "engine_prefill"
_OP = "engine_op"

# Host-gap cause taxonomy — mirrors profiler.CAUSE_NAMES (kept literal
# here so the ledger stays importable without the profiler module).
GAP_CAUSES = ("detok", "ws_send", "scheduler", "radix", "gc", "other")


def program_key(kind: str, **attrs: Any) -> str:
    """The canonical executable key: identical to the one
    ``note_compile`` builds from engine._note_compile's kind + attrs,
    so a step record's ``program`` attr and the compile ledger's
    ``by_key`` entries join exactly — /perf can say "this executable
    compiled at 14:03 AND has consumed 41% of device time since"."""
    return kind + "".join(f" {k}={attrs[k]}" for k in sorted(attrs))


def _detect_peak(table) -> tuple[float, str]:
    """(summed per-device peak from ``table``, device kind). 0.0 when
    the platform has no table entry — the figure then reports null."""
    try:
        import jax

        devs = jax.local_devices()
    except Exception:
        return 0.0, "unknown"
    if not devs:
        return 0.0, "unknown"
    kind = getattr(devs[0], "device_kind", "") or devs[0].platform
    low = str(kind).lower()
    for key, peak in table:
        if key in low:
            return peak * len(devs), str(kind)
    return 0.0, str(kind)


def detect_peak_tflops() -> tuple[float, str]:
    """(peak bf16 TFLOP/s per local device set, device kind)."""
    return _detect_peak(PEAK_TFLOPS_BF16)


def detect_peak_hbm_gbps() -> tuple[float, str]:
    """(peak HBM GB/s per local device set, device kind)."""
    return _detect_peak(PEAK_HBM_GBPS)


class PerfLedger:
    """Rolling attribution report over the tracer's step ring."""

    def __init__(self, *, tracer: Any = None,
                 window_s: float | None = None,
                 idle_gap_ms: float | None = None,
                 peak_tflops: float | None = None,
                 profiler: Any = None,
                 clock=time.monotonic):
        self.window_s = window_s if window_s is not None \
            else max(1.0, env_float("PERF_WINDOW_S", DEFAULT_WINDOW_S))
        self.idle_gap_ms = idle_gap_ms if idle_gap_ms is not None \
            else max(0.0, env_float("PERF_IDLE_GAP_MS",
                                    DEFAULT_IDLE_GAP_MS))
        # 0 = detect from the device kind lazily (first report).
        self._peak_override = peak_tflops if peak_tflops is not None \
            else env_float("PERF_PEAK_TFLOPS", 0.0)
        self._peak: tuple[float, str] | None = None
        self._hbm_override = env_float("PERF_PEAK_HBM_GBPS", 0.0)
        self._hbm_detected: tuple[float, str] | None = None
        self._tracer = tracer
        # The continuous stack sampler (observability/profiler.py):
        # supplies engine-thread cause observations and GC pause
        # intervals for the host-gap decomposition. Injectable in
        # tests; None = the process singleton, resolved lazily.
        self._profiler = profiler
        self._clock = clock
        self._lock = threading.Lock()
        # Model cost estimate (bind_model): FLOPs/token = _flops_base +
        # _flops_per_ctx * kv_len.
        self._model_name = ""
        self._num_slots = 0
        self._dtype = ""
        self._params = 0
        self._flops_base = 0.0
        self._flops_per_ctx = 0.0
        # KV-cache byte facts from the engine (bind_model): the honest
        # per-(slot, position)-row read cost across all layers — int8
        # rows + scales under KV_QUANT=int8, bf16 otherwise. The
        # FLOP/byte side of the attribution never assumes an element
        # size again.
        self._kv_quant = "none"
        self._kv_row_bytes = 0
        # Weight-byte facts (bind_model): what one decode step streams
        # of the RESIDENT weights — bf16, int8+scales, or nibble-packed
        # int4+scales (WEIGHT_QUANT). The bandwidth and FLOP/byte
        # figures read this instead of assuming params x 2 bytes.
        self._weight_quant = "off"
        self._weight_bytes_per_step = 0
        # Which decode attention path the engine routes steps through
        # (bind_model): attribution for the README perf table's
        # "kernel" column and docs/ROOFLINE.md rows.
        self._attention_kernel = ""
        # Compile ledger: key -> {kind, count, serving, first/last ts}.
        self._compiles: dict[str, dict[str, Any]] = {}
        # Per-connection token-journey attribution (observability/
        # journey.py): the serving layer feeds each finished journeyed
        # connection's hop totals here, so GET /perf shows where
        # *connections* (not just the process window) spent their wall
        # time — the per-connection form of the host-gap decomposition.
        self._journey_hops: dict[str, float] = {}
        self._journey_frames = 0
        self._journey_conns = 0
        m = get_metrics()
        self._m_busy = m.gauge(
            "perf_device_busy_frac",
            "fraction of the attribution window covered by engine "
            "device calls (decode dispatch-to-retirement union)")
        self._m_gap = m.gauge(
            "perf_host_gap_frac",
            "fraction of the attribution window spent in short gaps "
            "between device calls (host dispatch/consume overhead)")
        self._m_idle = m.gauge(
            "perf_idle_frac",
            "fraction of the attribution window with no device call "
            "and no work (gaps above PERF_IDLE_GAP_MS)")
        self._m_waste = m.gauge(
            "perf_padding_waste_frac",
            "fraction of computed token rows discarded as padding "
            "(inactive decode slots, rejected draft positions, "
            "prefill bucket/group padding)")
        self._m_occ = m.gauge(
            "perf_occupancy",
            "duration-weighted mean batch occupancy of decode calls")
        self._m_tok_s = m.gauge(
            "perf_useful_tok_s",
            "useful tokens per wall second over the attribution window "
            "(decode tokens consumed + prompt tokens prefilled)")
        self._m_mfu = m.gauge(
            "perf_mfu",
            "achieved model FLOP utilisation vs the device peak "
            "(0 when the peak is unknown; see perf_peak_tflops)")
        self._m_peak = m.gauge(
            "perf_peak_tflops",
            "roofline peak used for perf_mfu (0 = unknown device kind "
            "and PERF_PEAK_TFLOPS unset)")
        self._m_kv_gbps = m.gauge(
            "perf_kv_read_gbps",
            "KV-cache bytes the decode calls' attention streamed per "
            "wall second (honest element size: int8+scales under "
            "KV_QUANT=int8)")
        self._m_kv_bw = m.gauge(
            "perf_kv_bw_util",
            "KV attention-read bandwidth vs the device HBM peak "
            "(0 when the peak is unknown; see PERF_PEAK_HBM_GBPS)")
        self._m_w_gbps = m.gauge(
            "perf_weight_read_gbps",
            "weight bytes the decode calls streamed per wall second, at "
            "the resident tier's size (bf16 / int8+scales / int4+scales "
            "under WEIGHT_QUANT)")
        self._m_hbm_bw = m.gauge(
            "perf_hbm_bw_util",
            "combined weight + KV read bandwidth vs the device HBM peak "
            "(0 when the peak is unknown)")
        self._m_compiles = m.counter(
            "perf_serving_compiles_total",
            "jitted-executable compiles observed while serving traffic")
        self._m_prog_busy = m.labeled_gauge(
            "perf_program_busy_seconds",
            "device-busy seconds attributed to each jitted program "
            "over the attribution window (overlap split evenly; the "
            "family sums to device_busy_s)", label="program")
        self._m_prog_calls = m.labeled_gauge(
            "perf_program_calls",
            "device calls per jitted program over the attribution "
            "window", label="program")
        self._m_gap_cause_s = m.labeled_gauge(
            "perf_host_gap_cause_seconds",
            "host-gap seconds by sampled cause over the attribution "
            "window (gc from gc.callbacks pauses; the residual is "
            "'other', so the family sums to host_gap_s)",
            label="cause")
        self._m_gap_cause_frac = m.labeled_gauge(
            "perf_host_gap_cause_frac",
            "host-gap fraction of the attribution window by sampled "
            "cause (the family sums to host_gap_frac)", label="cause")

    # ---------------- wiring ----------------

    def _get_tracer(self):
        if self._tracer is None:
            from fasttalk_tpu.observability.trace import get_tracer

            self._tracer = get_tracer()
        return self._tracer

    def _get_profiler(self):
        if self._profiler is None:
            from fasttalk_tpu.observability.profiler import get_profiler

            self._profiler = get_profiler()
        return self._profiler

    def bind_model(self, model_cfg: Any, num_slots: int,
                   dtype: str = "", kv_quant: str = "none",
                   kv_row_bytes: int = 0, weight_quant: str = "off",
                   weight_bytes_per_step: int = 0,
                   attention_kernel: str = "") -> None:
        """Attach the served model's cost estimate (engine __init__).
        FLOPs/token = 2·params (every weight partakes in one multiply-
        accumulate) + 4·layers·q_dim·kv_len (QKᵀ and A·V per head).
        ``kv_row_bytes``: what one attention read of one (slot,
        position) row costs across all layers, at the cache's actual
        element size — int8 rows + scales under KV_QUANT=int8, never
        an assumed bf16. ``weight_bytes_per_step``: what one decode
        step streams of the resident weights, at THEIR actual size
        (WEIGHT_QUANT tier: bf16 / int8+scales / packed int4+scales).
        ``attention_kernel``: which decode attention path the engine
        routes steps through (xla_dense / xla_gather / pallas_dense /
        pallas_paged) — pure attribution, so the README perf table and
        docs/ROOFLINE.md can name the kernel per measured row."""
        with self._lock:
            self._model_name = getattr(model_cfg, "name", "")
            self._num_slots = num_slots
            self._dtype = dtype
            self._kv_quant = kv_quant
            self._kv_row_bytes = int(kv_row_bytes)
            self._weight_quant = weight_quant
            self._weight_bytes_per_step = int(weight_bytes_per_step)
            self._attention_kernel = attention_kernel
            self._params = int(model_cfg.param_count())
            self._flops_base = 2.0 * self._params
            self._flops_per_ctx = 4.0 * model_cfg.num_layers \
                * model_cfg.q_dim

    def call_flops(self, tokens: int, ctx: int) -> float:
        """FLOP estimate for one device call that computed ``tokens``
        useful tokens against a KV horizon of ``ctx`` (0.0 unbound)."""
        return tokens * (self._flops_base + self._flops_per_ctx * ctx)

    def note_compile(self, kind: str, serving: bool = False,
                     **attrs: Any) -> None:
        """Count one jitted-executable cache miss under its signature
        (the same kind+attrs key engine._note_compile events carry)."""
        key = program_key(kind, **attrs)
        now = time.time()
        with self._lock:
            entry = self._compiles.get(key)
            if entry is None:
                entry = {"key": key, "kind": kind, "count": 0,
                         "serving": 0, "first_ts": now, "last_ts": now}
                self._compiles[key] = entry
            entry["count"] += 1
            entry["last_ts"] = now
            if serving:
                entry["serving"] += 1
        if serving:
            self._m_compiles.inc()

    def note_journey(self, hops_ms: dict[str, float],
                     frames: int) -> None:
        """Accumulate one finished connection's per-hop wall-time
        totals (serving/server.py, JOURNEY_ENABLED streams only)."""
        with self._lock:
            for name, ms in hops_ms.items():
                self._journey_hops[name] = \
                    self._journey_hops.get(name, 0.0) + float(ms)
            self._journey_frames += int(frames)
            self._journey_conns += 1

    # ---------------- the report ----------------

    def _peak_tflops(self) -> tuple[float, str]:
        if self._peak_override > 0:
            return self._peak_override, "PERF_PEAK_TFLOPS"
        if self._peak is None:
            self._peak = detect_peak_tflops()
        return self._peak

    def _peak_hbm(self) -> tuple[float, str]:
        if self._hbm_override > 0:
            return self._hbm_override, "PERF_PEAK_HBM_GBPS"
        if self._hbm_detected is None:
            self._hbm_detected = detect_peak_hbm_gbps()
        return self._hbm_detected

    def report(self, now: float | None = None) -> dict[str, Any]:
        """The ``GET /perf`` body. ``now`` is on the step records'
        clock (time.monotonic in production; fake in tests)."""
        tracer = self._get_tracer()
        now = self._clock() if now is None else now
        records = [r for r in tracer.steps()
                   if r.name in (_STEP, _PREFILL, _OP)]
        horizon = now - self.window_s
        records = [r for r in records if r.t1 > horizon]
        records.sort(key=lambda r: r.t0)
        peak, device = self._peak_tflops()
        with self._lock:
            compiles = [dict(e) for e in self._compiles.values()]
            journey = {
                "connections": self._journey_conns,
                "frames": self._journey_frames,
                "hops_ms": {h: round(v, 3) for h, v
                            in sorted(self._journey_hops.items())},
            }
        compiles.sort(key=lambda e: -e["last_ts"])
        out: dict[str, Any] = {
            "enabled": tracer.enabled,
            "window_s": self.window_s,
            "idle_gap_ms": self.idle_gap_ms,
            "n_decode_calls": sum(1 for r in records
                                  if r.name == _STEP),
            "n_prefill_calls": sum(1 for r in records
                                   if r.name == _PREFILL),
            "n_op_calls": sum(1 for r in records if r.name == _OP),
            "model": {"name": self._model_name, "params": self._params,
                      "slots": self._num_slots, "dtype": self._dtype,
                      "kv_quant": self._kv_quant,
                      "kv_row_bytes": self._kv_row_bytes,
                      "weight_quant": self._weight_quant,
                      "weight_bytes_per_step":
                          self._weight_bytes_per_step,
                      "attention_kernel": self._attention_kernel},
            "compiles": {
                "total": sum(e["count"] for e in compiles),
                "serving": sum(e["serving"] for e in compiles),
                "by_key": compiles,
            },
            "journey": journey,
        }
        peak_hbm, hbm_src = self._peak_hbm()
        if not records:
            out["wall"] = None
            out["programs"] = {"total_busy_s": 0.0, "by_program": []}
            out["host_gap_causes"] = None
            out["tokens"] = None
            out["mfu"] = {"peak_tflops": peak or None,
                          "device": device, "mfu": None}
            out["kv"] = {"bytes_read": 0, "read_gbps": 0.0,
                         "peak_hbm_gbps": peak_hbm or None,
                         "hbm_source": hbm_src, "bw_util": None}
            out["weights"] = {"bytes_read": 0, "read_gbps": 0.0,
                              "bw_util": None}
            out["hbm"] = {"bytes_read": 0, "read_gbps": 0.0,
                          "peak_hbm_gbps": peak_hbm or None,
                          "bw_util": None, "flop_per_byte": None}
            out["ceiling"] = {"hbm_bytes_per_token": None,
                              "ceiling_tok_s": None,
                              "measured_tok_s": None,
                              "frac_of_ceiling": None}
            return out

        # Wall-time decomposition: union the (clipped) call intervals,
        # then classify every gap by the idle threshold. The window
        # starts at the first visible record (or the horizon, whichever
        # is later) so a freshly started process is not reported as
        # mostly idle.
        start = max(horizon, records[0].t0)
        clipped: list[tuple[float, float, str]] = []
        for r in records:
            a, b = max(r.t0, start), min(r.t1, now)
            if b > a:
                clipped.append(
                    (a, b, str(r.attrs.get("program",
                                           "(unattributed)"))))
        merged: list[tuple[float, float]] = []
        for a, b, _ in clipped:
            if merged and a <= merged[-1][1]:
                if b > merged[-1][1]:
                    merged[-1] = (merged[-1][0], b)
            else:
                merged.append((a, b))

        # Per-program attribution: a boundary sweep over the clipped
        # intervals splits every elementary covered segment evenly
        # among the programs running through it (pipelined decode
        # calls overlap on the in-order device queue — neither owns
        # the wall exclusively). device_busy_s is then DEFINED as the
        # fsum of the per-program totals, so the programs block
        # reconciles with it by construction, not by coincidence:
        # math.fsum over the reported busy_s values reproduces
        # total_busy_s bitwise (fsum is exact in any order).
        starts_at: dict[float, list[str]] = {}
        ends_at: dict[float, list[str]] = {}
        for a, b, prog in clipped:
            starts_at.setdefault(a, []).append(prog)
            ends_at.setdefault(b, []).append(prog)
        prog_parts: dict[str, list[float]] = {}
        active: dict[str, int] = {}
        prev: float | None = None
        for p in sorted(set(starts_at) | set(ends_at)):
            if prev is not None and active and p > prev:
                share = (p - prev) / sum(active.values())
                for prog, n in active.items():
                    prog_parts.setdefault(prog, []).append(share * n)
            for prog in ends_at.get(p, ()):
                active[prog] -= 1
                if not active[prog]:
                    del active[prog]
            for prog in starts_at.get(p, ()):
                active[prog] = active.get(prog, 0) + 1
            prev = p
        prog_busy = {prog: math.fsum(parts)
                     for prog, parts in prog_parts.items()}
        busy = math.fsum(prog_busy.values())

        gap_thresh = self.idle_gap_ms / 1000.0
        host_gap = idle = 0.0
        hg_intervals: list[tuple[float, float]] = []
        cursor = start
        for a, b in merged:
            g = a - cursor
            if g > 0:
                if g > gap_thresh:
                    idle += g
                else:
                    host_gap += g
                    hg_intervals.append((cursor, a))
            cursor = max(cursor, b)
        tail = now - cursor
        if tail > 0:
            if tail > gap_thresh:
                idle += tail
            else:
                host_gap += tail
                hg_intervals.append((cursor, now))
        window = now - start
        frac = (lambda x: round(x / window, 4)) if window > 0 \
            else (lambda x: 0.0)
        out["wall"] = {
            "window_s": round(window, 4),
            "device_busy_s": round(busy, 4),
            "host_gap_s": round(host_gap, 4),
            "idle_s": round(idle, 4),
            "device_busy_frac": frac(busy),
            "host_gap_frac": frac(host_gap),
            "idle_frac": frac(idle),
        }

        # Program stats (calls, tokens) ride the same records.
        prog_calls: dict[str, int] = {}
        prog_tokens: dict[str, int] = {}
        for r in records:
            prog = str(r.attrs.get("program", "(unattributed)"))
            prog_calls[prog] = prog_calls.get(prog, 0) + 1
            prog_tokens[prog] = prog_tokens.get(prog, 0) \
                + int(r.attrs.get("tokens", 0))
        by_program = [
            {"program": prog,
             # busy_s deliberately unrounded: the reconciliation
             # contract (fsum(busy_s) == total_busy_s) survives JSON
             # round-tripping only at full precision.
             "busy_s": prog_busy.get(prog, 0.0),
             "busy_frac_of_window": frac(prog_busy.get(prog, 0.0)),
             "frac_of_busy": round(prog_busy.get(prog, 0.0) / busy, 4)
             if busy > 0 else None,
             "calls": prog_calls.get(prog, 0),
             "tokens": prog_tokens.get(prog, 0)}
            for prog in prog_busy
        ]
        by_program.sort(key=lambda e: (-e["busy_s"], e["program"]))
        out["programs"] = {"total_busy_s": busy,
                           "by_program": by_program}

        # Host-gap cause decomposition: GC pauses are exact
        # (gc.callbacks intervals, clipped to the gap); the remainder
        # of each gap distributes proportionally to what the sampler
        # saw the engine thread doing inside it; whatever no evidence
        # claims — including every gap sampled as "other" and every
        # gap shorter than a sampler tick — lands in the residual
        # "other" bucket, which CLOSES the sum: by-cause seconds (and
        # fractions) total host_gap_s (host_gap_frac) by construction.
        try:
            prof = self._get_profiler()
        except Exception:
            prof = None
        named_parts: dict[str, list[float]] = {}
        for g0, g1 in hg_intervals:
            glen = g1 - g0
            gc_s = 0.0
            counts: dict[str, int] = {}
            if prof is not None:
                # A torn sampler (thread died mid-walk) costs this
                # gap's evidence, never the /perf report.
                try:
                    gc_s = min(glen,
                               max(0.0, prof.gc_overlap_s(g0, g1)))
                    counts = prof.causes_between(g0, g1)
                except Exception:
                    gc_s, counts = 0.0, {}
            if gc_s > 0:
                named_parts.setdefault("gc", []).append(gc_s)
            rest = glen - gc_s
            seen = sum(counts.values())
            if rest > 0 and seen > 0:
                for c in GAP_CAUSES:
                    if c in ("gc", "other"):
                        continue
                    n = counts.get(c, 0)
                    if n:
                        named_parts.setdefault(c, []).append(
                            rest * n / seen)
        named_s = {c: math.fsum(v) for c, v in named_parts.items()}
        other_s = max(0.0, host_gap - math.fsum(named_s.values()))
        cause_s = {c: named_s.get(c, 0.0) for c in GAP_CAUSES}
        cause_s["other"] = other_s
        out["host_gap_causes"] = {
            "host_gap_s": host_gap,
            "host_gap_frac": host_gap / window if window > 0 else 0.0,
            "sampler": {
                "enabled": bool(getattr(prof, "enabled", False)),
                "samples": int(getattr(prof, "samples", 0)),
            },
            "by_cause": {
                c: {"s": cause_s[c],
                    "frac": cause_s[c] / window if window > 0 else 0.0}
                for c in GAP_CAUSES
            },
        }

        # Useful tokens vs computed rows, occupancy, FLOPs, KV bytes.
        decode_tokens = prefill_tokens = 0
        computed_rows = 0
        occ_weight = occ_sum = 0.0
        flops = kv_bytes = weight_bytes = 0.0
        for r in records:
            a = r.attrs
            flops += float(a.get("flops", 0.0))
            if r.name == _STEP:
                decode_tokens += int(a.get("tokens", 0))
                computed_rows += int(a.get("rows",
                                           int(a.get("steps", 0))
                                           * int(a.get("slots", 0))))
                kv_bytes += float(a.get("kv_bytes", 0.0))
                weight_bytes += float(a.get("weight_bytes", 0.0))
                dur = max(0.0, r.t1 - r.t0)
                occ_weight += dur
                occ_sum += dur * float(a.get("occupancy", 0.0))
            elif r.name == _PREFILL:
                prefill_tokens += int(a.get("tokens", 0))
                computed_rows += int(a.get("rows", a.get("tokens", 0)))
        useful = decode_tokens + prefill_tokens
        out["tokens"] = {
            "decode_tokens": decode_tokens,
            "prefill_tokens": prefill_tokens,
            "computed_token_rows": computed_rows,
            "padding_waste_frac": round(1.0 - useful / computed_rows, 4)
            if computed_rows > 0 else None,
            "useful_tok_s": round(useful / window, 2)
            if window > 0 else None,
            "busy_tok_s": round(useful / busy, 2) if busy > 0 else None,
            "occupancy_mean": round(occ_sum / occ_weight, 4)
            if occ_weight > 0 else None,
        }
        achieved = flops / window / 1e12 if window > 0 else 0.0
        out["mfu"] = {
            "flops": flops,
            # Not rounded to fixed decimals: a tiny test model's real
            # achieved TFLOP/s (~1e-5) must not collapse to 0.
            "achieved_tflops": achieved,
            "peak_tflops": peak or None,
            "device": device,
            "mfu": round(achieved / peak, 6) if peak > 0 else None,
        }
        # KV attention-read bandwidth next to MFU: decode is
        # KV-read-bound at scale, and the element size here is the
        # cache's honest one (int8+scales under KV_QUANT=int8) — the
        # halved-bytes win is directly visible as read_gbps dropping
        # (same tok/s) or bw_util headroom appearing.
        kv_gbps = kv_bytes / window / 1e9 if window > 0 else 0.0
        out["kv"] = {
            "bytes_read": kv_bytes,
            "read_gbps": kv_gbps,
            "peak_hbm_gbps": peak_hbm or None,
            "hbm_source": hbm_src,
            "bw_util": round(kv_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
        }
        # Weight-read bandwidth at the RESIDENT tier's size (recorded
        # per step by the engine, never recomputed from an assumed
        # bf16): WEIGHT_QUANT=int4 shows up directly as read_gbps
        # dropping ~4x at the same tok/s. The combined "hbm" section is
        # the honest roofline operand — decode arithmetic intensity
        # (flop_per_byte) over weights + KV together.
        w_gbps = weight_bytes / window / 1e9 if window > 0 else 0.0
        out["weights"] = {
            "bytes_read": weight_bytes,
            "read_gbps": w_gbps,
            "bw_util": round(w_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
        }
        hbm_bytes = kv_bytes + weight_bytes
        hbm_gbps = kv_gbps + w_gbps
        out["hbm"] = {
            "bytes_read": hbm_bytes,
            "read_gbps": hbm_gbps,
            "peak_hbm_gbps": peak_hbm or None,
            "bw_util": round(hbm_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
            "flop_per_byte": round(flops / hbm_bytes, 4)
            if hbm_bytes > 0 else None,
        }
        # First-order roofline ceiling (docs/ROOFLINE.md): the tok/s
        # this window would have produced if HBM were saturated at the
        # device peak with the SAME measured per-useful-token byte
        # cost. frac_of_ceiling equals hbm.bw_util by construction —
        # stated here so "measured X tok/s of Y ceiling" reads off one
        # block without re-deriving the division.
        bpt = hbm_bytes / useful if useful > 0 else 0.0
        ceiling = peak_hbm * 1e9 / bpt if bpt > 0 and peak_hbm > 0 \
            else 0.0
        out["ceiling"] = {
            "hbm_bytes_per_token": round(bpt, 2) if bpt > 0 else None,
            "ceiling_tok_s": round(ceiling, 2) if ceiling > 0 else None,
            "measured_tok_s": out["tokens"]["useful_tok_s"],
            "frac_of_ceiling": round(hbm_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
        }
        return out

    def summary(self, now: float | None = None) -> dict[str, Any]:
        """Compact one-level digest (bench.py's JSON output)."""
        rep = self.report(now)
        wall = rep.get("wall") or {}
        toks = rep.get("tokens") or {}
        mfu = rep.get("mfu") or {}
        kv = rep.get("kv") or {}
        return {
            "device_busy_frac": wall.get("device_busy_frac"),
            "host_gap_frac": wall.get("host_gap_frac"),
            "idle_frac": wall.get("idle_frac"),
            "occupancy_mean": toks.get("occupancy_mean"),
            "padding_waste_frac": toks.get("padding_waste_frac"),
            "useful_tok_s": toks.get("useful_tok_s"),
            "mfu": mfu.get("mfu"),
            "achieved_tflops": mfu.get("achieved_tflops"),
            "kv_read_gbps": kv.get("read_gbps"),
            "kv_bw_util": kv.get("bw_util"),
            "weight_read_gbps": (rep.get("weights") or {}).get(
                "read_gbps"),
            "hbm_bw_util": (rep.get("hbm") or {}).get("bw_util"),
            "flop_per_byte": (rep.get("hbm") or {}).get("flop_per_byte"),
            "attention_kernel": (rep.get("model") or {}).get(
                "attention_kernel"),
            "ceiling_tok_s": (rep.get("ceiling") or {}).get(
                "ceiling_tok_s"),
            "frac_of_ceiling": (rep.get("ceiling") or {}).get(
                "frac_of_ceiling"),
            "serving_compiles": rep["compiles"]["serving"],
            "host_gap_causes": {
                c: round(v["frac"], 4) for c, v in
                ((rep.get("host_gap_causes") or {}).get("by_cause")
                 or {}).items()
            } or None,
            "programs_top": [
                {"program": e["program"],
                 "busy_s": round(e["busy_s"], 4),
                 "frac_of_busy": e["frac_of_busy"]}
                for e in (rep.get("programs") or {}).get(
                    "by_program", [])[:5]
            ],
        }

    def sample(self, now: float | None = None) -> None:
        """Refresh the perf_* gauges from a fresh report (called by the
        monitoring app before rendering /metrics, like the watchdog's
        heartbeat gauge)."""
        rep = self.report(now)
        wall = rep.get("wall") or {}
        toks = rep.get("tokens") or {}
        mfu = rep.get("mfu") or {}
        kv = rep.get("kv") or {}
        self._m_busy.set(wall.get("device_busy_frac") or 0.0)
        self._m_gap.set(wall.get("host_gap_frac") or 0.0)
        self._m_idle.set(wall.get("idle_frac") or 0.0)
        self._m_waste.set(toks.get("padding_waste_frac") or 0.0)
        self._m_occ.set(toks.get("occupancy_mean") or 0.0)
        self._m_tok_s.set(toks.get("useful_tok_s") or 0.0)
        self._m_mfu.set(mfu.get("mfu") or 0.0)
        self._m_peak.set(mfu.get("peak_tflops") or 0.0)
        self._m_kv_gbps.set(kv.get("read_gbps") or 0.0)
        self._m_kv_bw.set(kv.get("bw_util") or 0.0)
        self._m_w_gbps.set((rep.get("weights") or {}).get("read_gbps")
                           or 0.0)
        self._m_hbm_bw.set((rep.get("hbm") or {}).get("bw_util") or 0.0)
        progs = (rep.get("programs") or {}).get("by_program", [])
        self._m_prog_busy.set_all(
            {e["program"]: round(e["busy_s"], 6) for e in progs})
        self._m_prog_calls.set_all(
            {e["program"]: e["calls"] for e in progs})
        causes = ((rep.get("host_gap_causes") or {}).get("by_cause")
                  or {})
        self._m_gap_cause_s.set_all(
            {c: round(v["s"], 6) for c, v in causes.items()})
        self._m_gap_cause_frac.set_all(
            {c: round(v["frac"], 6) for c, v in causes.items()})

    def clear(self) -> None:
        """Test hook: drop the compile ledger IN PLACE. The model
        binding is construction-time wiring from a live engine (like
        cached metric objects) and survives — clearing it would orphan
        that engine's per-call FLOP feed for the rest of the process."""
        with self._lock:
            self._compiles.clear()
            self._journey_hops.clear()
            self._journey_frames = 0
            self._journey_conns = 0


_perf: PerfLedger | None = None


def get_perf() -> PerfLedger:
    global _perf
    if _perf is None:
        _perf = PerfLedger()
    return _perf


def reset_perf() -> None:
    """Test hook: clear the process-wide ledger in place."""
    if _perf is not None:
        _perf.clear()
