"""Performance attribution ledger: where engine wall time actually went.

PR 1 records *that* a decode call happened (the tracer's engine-step
ring) and PR 3 pages *when* latency promises break — neither explains
the gap between achieved throughput and what the hardware could do.
This module closes that gap with a rolling attribution report over the
engine's step/prefill telemetry:

- **Wall-time decomposition.** The step ring's records are intervals
  on the engine clock (dispatch → retirement for decode calls,
  dispatch for prefill calls). Their union is *device-busy* time; the
  gaps between them split into *host gap* (short — dispatch overhead,
  host-side token handling, admission work between calls) and *idle*
  (long — no work to run), by the ``PERF_IDLE_GAP_MS`` threshold
  (default 250). busy + host_gap + idle == the report window, exactly.
- **Padding waste.** Fixed shapes buy compile stability by computing
  rows that are thrown away: decode calls advance all S slots whether
  active or not (and speculative verify blocks compute draft+1
  positions of which only the accepted prefix is kept), and prefill
  pads prompts up to power-of-two buckets and group sizes. Every
  record carries the token rows it computed and the tokens that were
  actually useful; the waste fraction is 1 - useful/computed.
- **Occupancy-weighted useful-token throughput.** Useful tokens per
  wall second and per device-busy second, next to the duration-
  weighted mean batch occupancy — the number that says whether low
  tok/s is an empty batch or a slow step.
- **MFU.** Records carry a per-call FLOP estimate from the bound
  model config (2·params per token plus the attention term at the
  call's KV bucket); achieved FLOP/s over the window against the
  device's peak (detected from the device kind, overridable with
  ``PERF_PEAK_TFLOPS``) is the achieved-vs-peak roofline number the
  ROADMAP's "as fast as the hardware allows" is judged by.
- **Compile ledger.** Every ``_note_compile`` signature (warmup and
  serving-time) is counted per key, so "why did p99 spike" can be
  answered with "the 2048 prefill bucket compiled at 14:03" instead
  of a profiler session.

Exposed as ``GET /perf`` on the monitoring port, ``perf_*`` Prometheus
gauges (refreshed at scrape time), a ``--perf`` section in
``scripts/trace_report.py`` (offline, from a JSONL dump), and a
``perf`` block in bench.py's JSON output.

Same design constraints as the tracer: cheap (reads the existing ring;
recording adds one dict update per compile), thread-safe, clearable in
place for tests, fake-clock drivable (``report(now=...)``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from fasttalk_tpu.observability.events import env_float
from fasttalk_tpu.utils.metrics import get_metrics

DEFAULT_WINDOW_S = 60.0
DEFAULT_IDLE_GAP_MS = 250.0

# Peak dense bf16 TFLOP/s per chip by device-kind substring (public
# spec sheets); the roofline denominator when PERF_PEAK_TFLOPS is
# unset. Unknown kinds (CPU, new chips) report mfu: null rather than a
# made-up number.
PEAK_TFLOPS_BF16 = (
    ("v6e", 918.0), ("v6", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
)

# Peak HBM bandwidth GB/s per chip by device-kind substring (public
# spec sheets) — the roofline denominator for the KV-bandwidth
# utilisation figure (PERF_PEAK_HBM_GBPS overrides). Decode is
# KV-read-bound at scale, so this sits next to MFU: a call can be far
# off the FLOP roofline while saturating HBM — which is exactly what
# the int8 KV tier (KV_QUANT, docs/KVCACHE.md) halves.
PEAK_HBM_GBPS = (
    ("v6e", 1640.0), ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0), ("v5 lite", 819.0), ("v5litepod", 819.0),
    ("v4", 1228.0),
)

# Step-ring record names this ledger aggregates (engine/engine.py):
# decode calls (dispatch → retirement) and prefill calls (dispatch).
_STEP = "engine_step"
_PREFILL = "engine_prefill"


def _detect_peak(table) -> tuple[float, str]:
    """(summed per-device peak from ``table``, device kind). 0.0 when
    the platform has no table entry — the figure then reports null."""
    try:
        import jax

        devs = jax.local_devices()
    except Exception:
        return 0.0, "unknown"
    if not devs:
        return 0.0, "unknown"
    kind = getattr(devs[0], "device_kind", "") or devs[0].platform
    low = str(kind).lower()
    for key, peak in table:
        if key in low:
            return peak * len(devs), str(kind)
    return 0.0, str(kind)


def detect_peak_tflops() -> tuple[float, str]:
    """(peak bf16 TFLOP/s per local device set, device kind)."""
    return _detect_peak(PEAK_TFLOPS_BF16)


def detect_peak_hbm_gbps() -> tuple[float, str]:
    """(peak HBM GB/s per local device set, device kind)."""
    return _detect_peak(PEAK_HBM_GBPS)


class PerfLedger:
    """Rolling attribution report over the tracer's step ring."""

    def __init__(self, *, tracer: Any = None,
                 window_s: float | None = None,
                 idle_gap_ms: float | None = None,
                 peak_tflops: float | None = None,
                 clock=time.monotonic):
        self.window_s = window_s if window_s is not None \
            else max(1.0, env_float("PERF_WINDOW_S", DEFAULT_WINDOW_S))
        self.idle_gap_ms = idle_gap_ms if idle_gap_ms is not None \
            else max(0.0, env_float("PERF_IDLE_GAP_MS",
                                    DEFAULT_IDLE_GAP_MS))
        # 0 = detect from the device kind lazily (first report).
        self._peak_override = peak_tflops if peak_tflops is not None \
            else env_float("PERF_PEAK_TFLOPS", 0.0)
        self._peak: tuple[float, str] | None = None
        self._hbm_override = env_float("PERF_PEAK_HBM_GBPS", 0.0)
        self._hbm_detected: tuple[float, str] | None = None
        self._tracer = tracer
        self._clock = clock
        self._lock = threading.Lock()
        # Model cost estimate (bind_model): FLOPs/token = _flops_base +
        # _flops_per_ctx * kv_len.
        self._model_name = ""
        self._num_slots = 0
        self._dtype = ""
        self._params = 0
        self._flops_base = 0.0
        self._flops_per_ctx = 0.0
        # KV-cache byte facts from the engine (bind_model): the honest
        # per-(slot, position)-row read cost across all layers — int8
        # rows + scales under KV_QUANT=int8, bf16 otherwise. The
        # FLOP/byte side of the attribution never assumes an element
        # size again.
        self._kv_quant = "none"
        self._kv_row_bytes = 0
        # Weight-byte facts (bind_model): what one decode step streams
        # of the RESIDENT weights — bf16, int8+scales, or nibble-packed
        # int4+scales (WEIGHT_QUANT). The bandwidth and FLOP/byte
        # figures read this instead of assuming params x 2 bytes.
        self._weight_quant = "off"
        self._weight_bytes_per_step = 0
        # Which decode attention path the engine routes steps through
        # (bind_model): attribution for the README perf table's
        # "kernel" column and docs/ROOFLINE.md rows.
        self._attention_kernel = ""
        # Compile ledger: key -> {kind, count, serving, first/last ts}.
        self._compiles: dict[str, dict[str, Any]] = {}
        # Per-connection token-journey attribution (observability/
        # journey.py): the serving layer feeds each finished journeyed
        # connection's hop totals here, so GET /perf shows where
        # *connections* (not just the process window) spent their wall
        # time — the per-connection form of the host-gap decomposition.
        self._journey_hops: dict[str, float] = {}
        self._journey_frames = 0
        self._journey_conns = 0
        m = get_metrics()
        self._m_busy = m.gauge(
            "perf_device_busy_frac",
            "fraction of the attribution window covered by engine "
            "device calls (decode dispatch-to-retirement union)")
        self._m_gap = m.gauge(
            "perf_host_gap_frac",
            "fraction of the attribution window spent in short gaps "
            "between device calls (host dispatch/consume overhead)")
        self._m_idle = m.gauge(
            "perf_idle_frac",
            "fraction of the attribution window with no device call "
            "and no work (gaps above PERF_IDLE_GAP_MS)")
        self._m_waste = m.gauge(
            "perf_padding_waste_frac",
            "fraction of computed token rows discarded as padding "
            "(inactive decode slots, rejected draft positions, "
            "prefill bucket/group padding)")
        self._m_occ = m.gauge(
            "perf_occupancy",
            "duration-weighted mean batch occupancy of decode calls")
        self._m_tok_s = m.gauge(
            "perf_useful_tok_s",
            "useful tokens per wall second over the attribution window "
            "(decode tokens consumed + prompt tokens prefilled)")
        self._m_mfu = m.gauge(
            "perf_mfu",
            "achieved model FLOP utilisation vs the device peak "
            "(0 when the peak is unknown; see perf_peak_tflops)")
        self._m_peak = m.gauge(
            "perf_peak_tflops",
            "roofline peak used for perf_mfu (0 = unknown device kind "
            "and PERF_PEAK_TFLOPS unset)")
        self._m_kv_gbps = m.gauge(
            "perf_kv_read_gbps",
            "KV-cache bytes the decode calls' attention streamed per "
            "wall second (honest element size: int8+scales under "
            "KV_QUANT=int8)")
        self._m_kv_bw = m.gauge(
            "perf_kv_bw_util",
            "KV attention-read bandwidth vs the device HBM peak "
            "(0 when the peak is unknown; see PERF_PEAK_HBM_GBPS)")
        self._m_w_gbps = m.gauge(
            "perf_weight_read_gbps",
            "weight bytes the decode calls streamed per wall second, at "
            "the resident tier's size (bf16 / int8+scales / int4+scales "
            "under WEIGHT_QUANT)")
        self._m_hbm_bw = m.gauge(
            "perf_hbm_bw_util",
            "combined weight + KV read bandwidth vs the device HBM peak "
            "(0 when the peak is unknown)")
        self._m_compiles = m.counter(
            "perf_serving_compiles_total",
            "jitted-executable compiles observed while serving traffic")

    # ---------------- wiring ----------------

    def _get_tracer(self):
        if self._tracer is None:
            from fasttalk_tpu.observability.trace import get_tracer

            self._tracer = get_tracer()
        return self._tracer

    def bind_model(self, model_cfg: Any, num_slots: int,
                   dtype: str = "", kv_quant: str = "none",
                   kv_row_bytes: int = 0, weight_quant: str = "off",
                   weight_bytes_per_step: int = 0,
                   attention_kernel: str = "") -> None:
        """Attach the served model's cost estimate (engine __init__).
        FLOPs/token = 2·params (every weight partakes in one multiply-
        accumulate) + 4·layers·q_dim·kv_len (QKᵀ and A·V per head).
        ``kv_row_bytes``: what one attention read of one (slot,
        position) row costs across all layers, at the cache's actual
        element size — int8 rows + scales under KV_QUANT=int8, never
        an assumed bf16. ``weight_bytes_per_step``: what one decode
        step streams of the resident weights, at THEIR actual size
        (WEIGHT_QUANT tier: bf16 / int8+scales / packed int4+scales).
        ``attention_kernel``: which decode attention path the engine
        routes steps through (xla_dense / xla_gather / pallas_dense /
        pallas_paged) — pure attribution, so the README perf table and
        docs/ROOFLINE.md can name the kernel per measured row."""
        with self._lock:
            self._model_name = getattr(model_cfg, "name", "")
            self._num_slots = num_slots
            self._dtype = dtype
            self._kv_quant = kv_quant
            self._kv_row_bytes = int(kv_row_bytes)
            self._weight_quant = weight_quant
            self._weight_bytes_per_step = int(weight_bytes_per_step)
            self._attention_kernel = attention_kernel
            self._params = int(model_cfg.param_count())
            self._flops_base = 2.0 * self._params
            self._flops_per_ctx = 4.0 * model_cfg.num_layers \
                * model_cfg.q_dim

    def call_flops(self, tokens: int, ctx: int) -> float:
        """FLOP estimate for one device call that computed ``tokens``
        useful tokens against a KV horizon of ``ctx`` (0.0 unbound)."""
        return tokens * (self._flops_base + self._flops_per_ctx * ctx)

    def note_compile(self, kind: str, serving: bool = False,
                     **attrs: Any) -> None:
        """Count one jitted-executable cache miss under its signature
        (the same kind+attrs key engine._note_compile events carry)."""
        key = kind + "".join(f" {k}={attrs[k]}" for k in sorted(attrs))
        now = time.time()
        with self._lock:
            entry = self._compiles.get(key)
            if entry is None:
                entry = {"key": key, "kind": kind, "count": 0,
                         "serving": 0, "first_ts": now, "last_ts": now}
                self._compiles[key] = entry
            entry["count"] += 1
            entry["last_ts"] = now
            if serving:
                entry["serving"] += 1
        if serving:
            self._m_compiles.inc()

    def note_journey(self, hops_ms: dict[str, float],
                     frames: int) -> None:
        """Accumulate one finished connection's per-hop wall-time
        totals (serving/server.py, JOURNEY_ENABLED streams only)."""
        with self._lock:
            for name, ms in hops_ms.items():
                self._journey_hops[name] = \
                    self._journey_hops.get(name, 0.0) + float(ms)
            self._journey_frames += int(frames)
            self._journey_conns += 1

    # ---------------- the report ----------------

    def _peak_tflops(self) -> tuple[float, str]:
        if self._peak_override > 0:
            return self._peak_override, "PERF_PEAK_TFLOPS"
        if self._peak is None:
            self._peak = detect_peak_tflops()
        return self._peak

    def _peak_hbm(self) -> tuple[float, str]:
        if self._hbm_override > 0:
            return self._hbm_override, "PERF_PEAK_HBM_GBPS"
        if self._hbm_detected is None:
            self._hbm_detected = detect_peak_hbm_gbps()
        return self._hbm_detected

    def report(self, now: float | None = None) -> dict[str, Any]:
        """The ``GET /perf`` body. ``now`` is on the step records'
        clock (time.monotonic in production; fake in tests)."""
        tracer = self._get_tracer()
        now = self._clock() if now is None else now
        records = [r for r in tracer.steps()
                   if r.name in (_STEP, _PREFILL)]
        horizon = now - self.window_s
        records = [r for r in records if r.t1 > horizon]
        records.sort(key=lambda r: r.t0)
        peak, device = self._peak_tflops()
        with self._lock:
            compiles = [dict(e) for e in self._compiles.values()]
            journey = {
                "connections": self._journey_conns,
                "frames": self._journey_frames,
                "hops_ms": {h: round(v, 3) for h, v
                            in sorted(self._journey_hops.items())},
            }
        compiles.sort(key=lambda e: -e["last_ts"])
        out: dict[str, Any] = {
            "enabled": tracer.enabled,
            "window_s": self.window_s,
            "idle_gap_ms": self.idle_gap_ms,
            "n_decode_calls": sum(1 for r in records
                                  if r.name == _STEP),
            "n_prefill_calls": sum(1 for r in records
                                   if r.name == _PREFILL),
            "model": {"name": self._model_name, "params": self._params,
                      "slots": self._num_slots, "dtype": self._dtype,
                      "kv_quant": self._kv_quant,
                      "kv_row_bytes": self._kv_row_bytes,
                      "weight_quant": self._weight_quant,
                      "weight_bytes_per_step":
                          self._weight_bytes_per_step,
                      "attention_kernel": self._attention_kernel},
            "compiles": {
                "total": sum(e["count"] for e in compiles),
                "serving": sum(e["serving"] for e in compiles),
                "by_key": compiles,
            },
            "journey": journey,
        }
        peak_hbm, hbm_src = self._peak_hbm()
        if not records:
            out["wall"] = None
            out["tokens"] = None
            out["mfu"] = {"peak_tflops": peak or None,
                          "device": device, "mfu": None}
            out["kv"] = {"bytes_read": 0, "read_gbps": 0.0,
                         "peak_hbm_gbps": peak_hbm or None,
                         "hbm_source": hbm_src, "bw_util": None}
            out["weights"] = {"bytes_read": 0, "read_gbps": 0.0,
                              "bw_util": None}
            out["hbm"] = {"bytes_read": 0, "read_gbps": 0.0,
                          "peak_hbm_gbps": peak_hbm or None,
                          "bw_util": None, "flop_per_byte": None}
            out["ceiling"] = {"hbm_bytes_per_token": None,
                              "ceiling_tok_s": None,
                              "measured_tok_s": None,
                              "frac_of_ceiling": None}
            return out

        # Wall-time decomposition: union the (clipped) call intervals,
        # then classify every gap by the idle threshold. The window
        # starts at the first visible record (or the horizon, whichever
        # is later) so a freshly started process is not reported as
        # mostly idle.
        start = max(horizon, records[0].t0)
        intervals = [(max(r.t0, start), min(r.t1, now)) for r in records]
        intervals = [(a, b) for a, b in intervals if b > a]
        merged: list[tuple[float, float]] = []
        for a, b in intervals:
            if merged and a <= merged[-1][1]:
                if b > merged[-1][1]:
                    merged[-1] = (merged[-1][0], b)
            else:
                merged.append((a, b))
        busy = sum(b - a for a, b in merged)
        gap_thresh = self.idle_gap_ms / 1000.0
        host_gap = idle = 0.0
        cursor = start
        for a, b in merged:
            g = a - cursor
            if g > 0:
                if g > gap_thresh:
                    idle += g
                else:
                    host_gap += g
            cursor = max(cursor, b)
        tail = now - cursor
        if tail > 0:
            if tail > gap_thresh:
                idle += tail
            else:
                host_gap += tail
        window = now - start
        frac = (lambda x: round(x / window, 4)) if window > 0 \
            else (lambda x: 0.0)
        out["wall"] = {
            "window_s": round(window, 4),
            "device_busy_s": round(busy, 4),
            "host_gap_s": round(host_gap, 4),
            "idle_s": round(idle, 4),
            "device_busy_frac": frac(busy),
            "host_gap_frac": frac(host_gap),
            "idle_frac": frac(idle),
        }

        # Useful tokens vs computed rows, occupancy, FLOPs, KV bytes.
        decode_tokens = prefill_tokens = 0
        computed_rows = 0
        occ_weight = occ_sum = 0.0
        flops = kv_bytes = weight_bytes = 0.0
        for r in records:
            a = r.attrs
            flops += float(a.get("flops", 0.0))
            if r.name == _STEP:
                decode_tokens += int(a.get("tokens", 0))
                computed_rows += int(a.get("rows",
                                           int(a.get("steps", 0))
                                           * int(a.get("slots", 0))))
                kv_bytes += float(a.get("kv_bytes", 0.0))
                weight_bytes += float(a.get("weight_bytes", 0.0))
                dur = max(0.0, r.t1 - r.t0)
                occ_weight += dur
                occ_sum += dur * float(a.get("occupancy", 0.0))
            else:
                prefill_tokens += int(a.get("tokens", 0))
                computed_rows += int(a.get("rows", a.get("tokens", 0)))
        useful = decode_tokens + prefill_tokens
        out["tokens"] = {
            "decode_tokens": decode_tokens,
            "prefill_tokens": prefill_tokens,
            "computed_token_rows": computed_rows,
            "padding_waste_frac": round(1.0 - useful / computed_rows, 4)
            if computed_rows > 0 else None,
            "useful_tok_s": round(useful / window, 2)
            if window > 0 else None,
            "busy_tok_s": round(useful / busy, 2) if busy > 0 else None,
            "occupancy_mean": round(occ_sum / occ_weight, 4)
            if occ_weight > 0 else None,
        }
        achieved = flops / window / 1e12 if window > 0 else 0.0
        out["mfu"] = {
            "flops": flops,
            # Not rounded to fixed decimals: a tiny test model's real
            # achieved TFLOP/s (~1e-5) must not collapse to 0.
            "achieved_tflops": achieved,
            "peak_tflops": peak or None,
            "device": device,
            "mfu": round(achieved / peak, 6) if peak > 0 else None,
        }
        # KV attention-read bandwidth next to MFU: decode is
        # KV-read-bound at scale, and the element size here is the
        # cache's honest one (int8+scales under KV_QUANT=int8) — the
        # halved-bytes win is directly visible as read_gbps dropping
        # (same tok/s) or bw_util headroom appearing.
        kv_gbps = kv_bytes / window / 1e9 if window > 0 else 0.0
        out["kv"] = {
            "bytes_read": kv_bytes,
            "read_gbps": kv_gbps,
            "peak_hbm_gbps": peak_hbm or None,
            "hbm_source": hbm_src,
            "bw_util": round(kv_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
        }
        # Weight-read bandwidth at the RESIDENT tier's size (recorded
        # per step by the engine, never recomputed from an assumed
        # bf16): WEIGHT_QUANT=int4 shows up directly as read_gbps
        # dropping ~4x at the same tok/s. The combined "hbm" section is
        # the honest roofline operand — decode arithmetic intensity
        # (flop_per_byte) over weights + KV together.
        w_gbps = weight_bytes / window / 1e9 if window > 0 else 0.0
        out["weights"] = {
            "bytes_read": weight_bytes,
            "read_gbps": w_gbps,
            "bw_util": round(w_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
        }
        hbm_bytes = kv_bytes + weight_bytes
        hbm_gbps = kv_gbps + w_gbps
        out["hbm"] = {
            "bytes_read": hbm_bytes,
            "read_gbps": hbm_gbps,
            "peak_hbm_gbps": peak_hbm or None,
            "bw_util": round(hbm_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
            "flop_per_byte": round(flops / hbm_bytes, 4)
            if hbm_bytes > 0 else None,
        }
        # First-order roofline ceiling (docs/ROOFLINE.md): the tok/s
        # this window would have produced if HBM were saturated at the
        # device peak with the SAME measured per-useful-token byte
        # cost. frac_of_ceiling equals hbm.bw_util by construction —
        # stated here so "measured X tok/s of Y ceiling" reads off one
        # block without re-deriving the division.
        bpt = hbm_bytes / useful if useful > 0 else 0.0
        ceiling = peak_hbm * 1e9 / bpt if bpt > 0 and peak_hbm > 0 \
            else 0.0
        out["ceiling"] = {
            "hbm_bytes_per_token": round(bpt, 2) if bpt > 0 else None,
            "ceiling_tok_s": round(ceiling, 2) if ceiling > 0 else None,
            "measured_tok_s": out["tokens"]["useful_tok_s"],
            "frac_of_ceiling": round(hbm_gbps / peak_hbm, 6)
            if peak_hbm > 0 else None,
        }
        return out

    def summary(self, now: float | None = None) -> dict[str, Any]:
        """Compact one-level digest (bench.py's JSON output)."""
        rep = self.report(now)
        wall = rep.get("wall") or {}
        toks = rep.get("tokens") or {}
        mfu = rep.get("mfu") or {}
        kv = rep.get("kv") or {}
        return {
            "device_busy_frac": wall.get("device_busy_frac"),
            "host_gap_frac": wall.get("host_gap_frac"),
            "idle_frac": wall.get("idle_frac"),
            "occupancy_mean": toks.get("occupancy_mean"),
            "padding_waste_frac": toks.get("padding_waste_frac"),
            "useful_tok_s": toks.get("useful_tok_s"),
            "mfu": mfu.get("mfu"),
            "achieved_tflops": mfu.get("achieved_tflops"),
            "kv_read_gbps": kv.get("read_gbps"),
            "kv_bw_util": kv.get("bw_util"),
            "weight_read_gbps": (rep.get("weights") or {}).get(
                "read_gbps"),
            "hbm_bw_util": (rep.get("hbm") or {}).get("bw_util"),
            "flop_per_byte": (rep.get("hbm") or {}).get("flop_per_byte"),
            "attention_kernel": (rep.get("model") or {}).get(
                "attention_kernel"),
            "ceiling_tok_s": (rep.get("ceiling") or {}).get(
                "ceiling_tok_s"),
            "frac_of_ceiling": (rep.get("ceiling") or {}).get(
                "frac_of_ceiling"),
            "serving_compiles": rep["compiles"]["serving"],
        }

    def sample(self, now: float | None = None) -> None:
        """Refresh the perf_* gauges from a fresh report (called by the
        monitoring app before rendering /metrics, like the watchdog's
        heartbeat gauge)."""
        rep = self.report(now)
        wall = rep.get("wall") or {}
        toks = rep.get("tokens") or {}
        mfu = rep.get("mfu") or {}
        kv = rep.get("kv") or {}
        self._m_busy.set(wall.get("device_busy_frac") or 0.0)
        self._m_gap.set(wall.get("host_gap_frac") or 0.0)
        self._m_idle.set(wall.get("idle_frac") or 0.0)
        self._m_waste.set(toks.get("padding_waste_frac") or 0.0)
        self._m_occ.set(toks.get("occupancy_mean") or 0.0)
        self._m_tok_s.set(toks.get("useful_tok_s") or 0.0)
        self._m_mfu.set(mfu.get("mfu") or 0.0)
        self._m_peak.set(mfu.get("peak_tflops") or 0.0)
        self._m_kv_gbps.set(kv.get("read_gbps") or 0.0)
        self._m_kv_bw.set(kv.get("bw_util") or 0.0)
        self._m_w_gbps.set((rep.get("weights") or {}).get("read_gbps")
                           or 0.0)
        self._m_hbm_bw.set((rep.get("hbm") or {}).get("bw_util") or 0.0)

    def clear(self) -> None:
        """Test hook: drop the compile ledger IN PLACE. The model
        binding is construction-time wiring from a live engine (like
        cached metric objects) and survives — clearing it would orphan
        that engine's per-call FLOP feed for the rest of the process."""
        with self._lock:
            self._compiles.clear()
            self._journey_hops.clear()
            self._journey_frames = 0
            self._journey_conns = 0


_perf: PerfLedger | None = None


def get_perf() -> PerfLedger:
    global _perf
    if _perf is None:
        _perf = PerfLedger()
    return _perf


def reset_perf() -> None:
    """Test hook: clear the process-wide ledger in place."""
    if _perf is not None:
        _perf.clear()
