"""Per-token journey waterfall (docs/OBSERVABILITY.md "The token
journey").

The perf ledger decomposes *process* wall time (device busy / host gap
/ idle); this module decomposes *one connection's* latency the same
way, per emitted token frame: every frame's path from the engine's
blocking device fetch through detokenize, the event-loop hand-off and
the WS write is cut into named hops whose durations **telescope** —
consecutive boundary timestamps, so the hop sums reconcile with
wall-clock TTFT and inter-token gaps exactly by construction, not
within a fudge factor. (The JOURNEY_TOL knob exists for *derived*
checks in scripts/trace_report.py, where rounding and frame caps
apply.)

Boundaries per frame (all ``time.monotonic()``):

  prev ──engine──► w ──device_fetch──► f ──detok_emit──► e
       ──loop_dequeue──► dq ──ws_write──► sent

- ``prev``: the request start (frame 0 — so the "engine" hop covers
  queue wait + prefill + decode compute) or the previous frame's
  ``sent`` (the inter-token decomposition).
- ``w``/``f``/``e``: stamped on the engine thread when the request
  opted in (engine/engine.py attaches them to the token event as the
  ``"j"`` dict): the blocking device-fetch wait start, the fetch
  landing, and the event enqueue. Absent for remote engines — the
  frame degrades to engine → dequeue → ws_write.
- ``dq``/``sent``: stamped on the serving loop (serving/server.py).

Out-of-order stamps (a retirement that batched several requests'
flushes) are clamped forward, which redistributes between adjacent
hops but preserves the telescoping sum.

The recorder is per-connection, bounded (frame arrays cap at
``max_frames``; later frames still count in the totals), and feeds
three surfaces: the ``journey`` block in the WS ``response_complete``
stats, one ``token_journey`` summary span on the request trace (the
offline ``trace_report.py --journey`` input), and the perf ledger's
per-connection host-gap attribution.
"""

from __future__ import annotations

import math
from typing import Any

HOPS = ("engine", "device_fetch", "detok_emit", "loop_dequeue",
        "ws_write")

# Per-hop frame arrays kept on the token_journey span: enough for
# percentile math offline, bounded so a max_tokens=4096 stream cannot
# bloat the trace ring.
MAX_FRAMES = 512


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return sorted_vals[idx]


class JourneyRecorder:
    """Accumulates one connection's per-frame hop decomposition."""

    def __init__(self, start_mono: float,
                 max_frames: int = MAX_FRAMES):
        self.start = start_mono
        self.max_frames = max(1, max_frames)
        self.frames = 0
        self.dropped = 0
        self._prev = start_mono
        self._last_sent: float | None = None
        self._first_sent: float | None = None
        # hop -> per-frame durations (ms), capped at max_frames
        self._hop_ms: dict[str, list[float]] = {h: [] for h in HOPS}
        # hop -> total over ALL frames (the caps never skew the sums)
        self._hop_total: dict[str, float] = {h: 0.0 for h in HOPS}
        self._ttft_hops: dict[str, float] | None = None

    def frame(self, j: dict[str, float] | None, t_dequeue: float,
              t_sent: float) -> None:
        """Record one emitted token frame. ``j`` is the engine's stamp
        dict ({"w","f","e"}, monotonic) or None for engines that don't
        stamp (remote backends) — the engine-side hops then fold into
        "engine"."""
        j = j or {}
        b = self._prev
        bounds: list[float] = []
        for t in (j.get("w"), j.get("f"), j.get("e"), t_dequeue,
                  t_sent):
            # Clamp forward: boundaries must not run backwards or the
            # telescoping sum breaks.
            b = b if t is None or t < b else t
            bounds.append(b)
        prev = self._prev
        hops: dict[str, float] = {}
        for name, bound in zip(HOPS, bounds):
            hops[name] = (bound - prev) * 1000.0
            prev = bound
        for name, ms in hops.items():
            self._hop_total[name] += ms
            if self.frames < self.max_frames:
                self._hop_ms[name].append(ms)
        if self.frames >= self.max_frames:
            self.dropped += 1
        if self.frames == 0:
            self._ttft_hops = dict(hops)
            self._first_sent = bounds[-1]
        self.frames += 1
        self._last_sent = bounds[-1]
        self._prev = bounds[-1]

    # ---------------- read side ----------------

    def summary(self) -> dict[str, Any]:
        """The connection's waterfall: hop totals + percentiles, the
        TTFT decomposition, and the reconciliation check (hop sums vs
        wall clock — 1.0 by construction)."""
        wall_ms = ((self._last_sent - self.start) * 1000.0
                   if self._last_sent is not None else 0.0)
        hops_sum = sum(self._hop_total.values())
        out: dict[str, Any] = {
            "frames": self.frames,
            "wall_ms": round(wall_ms, 3),
            "hops_sum_ms": round(hops_sum, 3),
            "reconciliation": round(hops_sum / wall_ms, 4)
            if wall_ms > 0 else None,
            "hops_ms": {h: round(v, 3)
                        for h, v in self._hop_total.items()},
        }
        if self._first_sent is not None:
            out["ttft_ms"] = round(
                (self._first_sent - self.start) * 1000.0, 3)
        if self._ttft_hops is not None:
            out["ttft_hops_ms"] = {h: round(v, 3)
                                   for h, v in self._ttft_hops.items()}
        p50: dict[str, float] = {}
        p99: dict[str, float] = {}
        for h, vals in self._hop_ms.items():
            sv = sorted(vals)
            p50[h] = round(_percentile(sv, 50), 3)
            p99[h] = round(_percentile(sv, 99), 3)
        out["hop_p50_ms"] = p50
        out["hop_p99_ms"] = p99
        if self.dropped:
            out["frames_uncounted_in_percentiles"] = self.dropped
        return out

    def span_attrs(self) -> dict[str, Any]:
        """Attrs for the once-per-request ``token_journey`` summary
        span: the summary plus the (capped) per-frame hop arrays the
        offline report computes percentiles from."""
        attrs = self.summary()
        attrs["frames_ms"] = {h: [round(v, 3) for v in vals]
                              for h, vals in self._hop_ms.items()}
        return attrs
