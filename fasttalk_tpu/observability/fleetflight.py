"""Fleet incident flight recorder: cross-replica evidence capture.

The per-process FlightRecorder (observability/flight.py) snapshots ONE
process's rings when that process pages. A fleet incident — a probe
partition, a failover burst, an SLO page on any replica — scatters its
evidence across every replica's rings, and each replica's own recorder
only sees its local fraction (a dead replica's survivor peers hold the
interesting half). This recorder runs ROUTER-side and fans bundle
collection out to every live replica into ONE incident directory:

    <FLEET_FLIGHT_DIR>/<stamp>-<n>/
      manifest.json            trigger, per-replica status, errors
      router.json              fleet_stats (placements, probes, KV)
      events.json              the router process's event tail
      slo.json                 the router process's SLO report
      fleet_metrics.prom       the label-merged fleet exposition
      replicas/<id>/health.json   per-replica probe signals
      replicas/<id>/slo.json      remote replica's /slo (HTTP)
      replicas/<id>/metrics.prom  remote replica's /metrics (HTTP)
      traces/<request_id>.json    stitched traces of in-flight requests

**Triggers** (an EventLog listener, installed by the serving layer
when the engine is a FleetRouter):

- ``router_partition`` — a replica probed dead
- a ``router_failover`` burst — ``FLEET_FLIGHT_FAILOVER_BURST``
  (default 3) failovers within ``FLEET_FLIGHT_WINDOW_S`` (default 60):
  one failover is routine, a burst is a dying fleet
- ``replica_slo_page`` — a remote replica's probe body reports a
  page-severity burn (router/router.py probe_once emits it on the
  transition)
- ``slo_burn_start`` with ``state: "page"`` — the local process's own
  SLO engine paged

Same discipline as flight.py: O(1) on the emitter's thread, writes on
a daemon thread (``inline=True`` for tests), at most one bundle per
``FLEET_FLIGHT_MIN_INTERVAL_S``, newest ``FLEET_FLIGHT_MAX_BUNDLES``
kept, every section individually fault-isolated — one unreachable
replica costs its directory, not the incident.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any

from fasttalk_tpu.observability.events import (Event, EventLog,
                                               env_float, get_events)
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("observability.fleetflight")

DEFAULT_DIR = "/tmp/fasttalk-tpu-fleet-flight"
DEFAULT_MAX_BUNDLES = 4
DEFAULT_MIN_INTERVAL_S = 120.0
DEFAULT_FAILOVER_BURST = 3
DEFAULT_WINDOW_S = 60.0
DEFAULT_EVENTS_TAIL = 256
# Stitched traces per bundle: enough for every in-flight request of a
# sanely sized fleet, bounded against a pathological one.
MAX_TRACES = 16


class FleetFlightRecorder:
    """Router-side incident bundle collector; constructed by the
    serving launcher when the engine is a FleetRouter, standalone-
    constructible in tests (injectable clock, inline writes)."""

    def __init__(self, router: Any, *, enabled: bool | None = None,
                 base_dir: str | None = None,
                 max_bundles: int | None = None,
                 min_interval_s: float | None = None,
                 failover_burst: int | None = None,
                 window_s: float | None = None,
                 events_tail: int | None = None,
                 clock=time.time, inline: bool = False):
        if enabled is None:
            enabled = os.getenv("FLEET_FLIGHT_ENABLED",
                                "true").strip().lower() \
                in ("1", "true", "yes", "on")
        self.enabled = enabled
        self.router = router
        self.base_dir = base_dir if base_dir is not None \
            else (os.getenv("FLEET_FLIGHT_DIR", "").strip()
                  or DEFAULT_DIR)
        self.max_bundles = max_bundles if max_bundles is not None \
            else max(1, int(env_float("FLEET_FLIGHT_MAX_BUNDLES",
                                      DEFAULT_MAX_BUNDLES)))
        self.min_interval_s = min_interval_s \
            if min_interval_s is not None \
            else max(0.0, env_float("FLEET_FLIGHT_MIN_INTERVAL_S",
                                    DEFAULT_MIN_INTERVAL_S))
        self.failover_burst = failover_burst \
            if failover_burst is not None \
            else max(2, int(env_float("FLEET_FLIGHT_FAILOVER_BURST",
                                      DEFAULT_FAILOVER_BURST)))
        self.window_s = window_s if window_s is not None \
            else max(1.0, env_float("FLEET_FLIGHT_WINDOW_S",
                                    DEFAULT_WINDOW_S))
        self.events_tail = events_tail if events_tail is not None \
            else max(1, int(env_float("FLIGHT_EVENTS_TAIL",
                                      DEFAULT_EVENTS_TAIL)))
        self._clock = clock
        self._inline = inline
        self._lock = threading.Lock()
        self._last_bundle_ts: float | None = None
        self._writing = False
        self._failover_ts: list[float] = []
        self._installed_on: EventLog | None = None
        self.bundles_written = 0
        self.triggers_suppressed = 0

    # ---------------- wiring ----------------

    def install(self, events: EventLog | None = None) -> None:
        events = events if events is not None else get_events()
        events.add_listener(self.on_event)
        self._installed_on = events

    def uninstall(self) -> None:
        if self._installed_on is not None:
            self._installed_on.remove_listener(self.on_event)
            self._installed_on = None

    # ---------------- triggers ----------------

    def on_event(self, ev: Event) -> None:
        """EventLog listener — O(1) checks on the emitter's thread."""
        if not self.enabled:
            return
        kind = ev.kind
        if kind in ("router_partition", "replica_slo_page"):
            self.trigger(f"{kind}:{ev.attrs.get('replica', '?')}",
                         kind=kind)
        elif kind == "slo_burn_start":
            if ev.attrs.get("state") == "page":
                self.trigger(f"slo_page:{ev.attrs.get('cls', '?')}",
                             kind=kind)
        elif kind == "router_failover":
            now = self._clock()
            with self._lock:
                self._failover_ts.append(now)
                horizon = now - self.window_s
                self._failover_ts = [t for t in self._failover_ts
                                     if t >= horizon]
                burst = len(self._failover_ts) >= self.failover_burst
                if burst:
                    self._failover_ts.clear()
            if burst:
                self.trigger("failover_burst", kind=kind)

    def trigger(self, reason: str, kind: str = "manual",
                force: bool = False,
                now: float | None = None) -> str | None:
        """Request a fleet bundle; same contract as
        FlightRecorder.trigger (rate-limited, one writer, ``force``
        bypasses the window without consuming it)."""
        if not self.enabled:
            return None
        now = self._clock() if now is None else now
        with self._lock:
            if self._writing:
                self.triggers_suppressed += 1
                return None
            if not force and self._last_bundle_ts is not None \
                    and now - self._last_bundle_ts < self.min_interval_s:
                self.triggers_suppressed += 1
                return None
            self._writing = True
        try:
            stamp = time.strftime("%Y%m%d-%H%M%S",
                                  time.localtime(time.time()))
            bundle_dir = os.path.join(
                self.base_dir, f"{stamp}-{self.bundles_written:03d}")
            os.makedirs(bundle_dir, exist_ok=True)
        except OSError as e:
            log.error(f"fleet flight bundle dir failed: {e}")
            with self._lock:
                self._writing = False
            return None
        if not force:
            with self._lock:
                self._last_bundle_ts = now
        if self._inline:
            self._write_bundle(bundle_dir, reason, kind, now)
        else:
            threading.Thread(
                target=self._write_bundle, name="fleet-flight",
                args=(bundle_dir, reason, kind, now), daemon=True,
            ).start()
        return bundle_dir

    # ---------------- the bundle ----------------

    def _write_bundle(self, bundle_dir: str, reason: str, kind: str,
                      now: float) -> None:
        t0 = time.monotonic()
        errors: dict[str, str] = {}

        def section(name: str, build) -> None:
            try:
                payload = build()
                path = os.path.join(bundle_dir, name)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "w", encoding="utf-8") as fp:
                    if isinstance(payload, str):
                        fp.write(payload)
                    else:
                        json.dump(payload, fp, ensure_ascii=False,
                                  default=str, indent=1)
            except Exception as e:  # one broken source costs one file
                errors[name] = str(e)

        def events_tail():
            src = self._installed_on if self._installed_on is not None \
                else get_events()
            return src.recent(limit=self.events_tail)

        def slo_report():
            from fasttalk_tpu.observability.slo import get_slo

            return get_slo().snapshot()

        def profile_collapsed():
            from fasttalk_tpu.observability.profiler import get_profiler

            return get_profiler().collapsed()

        def profile_report():
            from fasttalk_tpu.observability.profiler import get_profiler

            return get_profiler().report()

        router = self.router
        section("router.json", router.fleet_stats)
        section("events.json", events_tail)
        section("slo.json", slo_report)
        section("fleet_metrics.prom", router.fleet_metrics)
        # The router process's own continuous-profiler aggregate: a
        # fleet incident's routing-side half (probe loops, failover
        # bursts) happens on THIS process's threads.
        section("profile.txt", profile_collapsed)
        section("profile.json", profile_report)

        replica_status: dict[str, dict[str, Any]] = {}
        for h in list(getattr(router, "replicas", ())):
            rid = h.replica_id
            replica_status[rid] = {"state": h.state,
                                   "alive": h.alive(),
                                   "remote": hasattr(h, "base_url")}
            section(f"replicas/{rid}/health.json", h.to_dict)
            if not h.alive():
                replica_status[rid]["collected"] = False
                continue
            if hasattr(h, "base_url"):
                # Remote: its rings live in its process — fetch them.
                section(f"replicas/{rid}/metrics.prom",
                        lambda h=h: h.fetch_metrics() or "")
                section(f"replicas/{rid}/slo.json",
                        lambda h=h: h.fetch_slo() or {})
            replica_status[rid]["collected"] = \
                f"replicas/{rid}/health.json" not in errors

        # Stitched traces of in-flight requests: the requests the
        # incident interrupted, reassembled across every replica that
        # held a fragment.
        trace_ids: list[str] = []
        try:
            from fasttalk_tpu.observability.trace import get_tracer

            inflight = [t["request_id"] for t
                        in get_tracer().inflight_summary()]
            for rid in inflight[:MAX_TRACES]:
                safe = rid.replace("/", "_").replace(":", "_")
                section(f"traces/{safe}.json",
                        lambda rid=rid: router.stitched_trace(rid)
                        or {})
                trace_ids.append(rid)
        except Exception as e:
            errors["traces"] = str(e)

        manifest = {
            "reason": reason,
            "trigger_kind": kind,
            "ts": time.time(),
            "trigger_clock": now,
            "write_s": round(time.monotonic() - t0, 3),
            "replicas": replica_status,
            "stitched_traces": trace_ids,
            **({"errors": errors} if errors else {}),
        }
        try:
            with open(os.path.join(bundle_dir, "manifest.json"), "w",
                      encoding="utf-8") as fp:
                json.dump(manifest, fp, indent=1, default=str)
        except OSError as e:
            log.error(f"fleet flight manifest failed: {e}")
        self.bundles_written += 1
        self._prune()
        log.warning(
            f"fleet flight bundle written: {bundle_dir} (reason "
            f"{reason}{', errors ' + str(sorted(errors)) if errors else ''})")
        with self._lock:
            self._writing = False

    def _prune(self) -> None:
        try:
            entries = sorted(
                d for d in os.listdir(self.base_dir)
                if os.path.isdir(os.path.join(self.base_dir, d)))
        except OSError:
            return
        for stale in entries[:max(0, len(entries) - self.max_bundles)]:
            shutil.rmtree(os.path.join(self.base_dir, stale),
                          ignore_errors=True)

    # ---------------- read side ----------------

    def list_bundles(self) -> list[str]:
        try:
            return sorted(
                os.path.join(self.base_dir, d)
                for d in os.listdir(self.base_dir)
                if os.path.isdir(os.path.join(self.base_dir, d)))
        except OSError:
            return []

    def stats(self) -> dict[str, Any]:
        with self._lock:
            last = self._last_bundle_ts
        return {
            "enabled": self.enabled,
            "dir": self.base_dir,
            "bundles_written": self.bundles_written,
            "triggers_suppressed": self.triggers_suppressed,
            "last_bundle_ts": last,
            "min_interval_s": self.min_interval_s,
            "max_bundles": self.max_bundles,
            "failover_burst": self.failover_burst,
            "window_s": self.window_s,
        }
