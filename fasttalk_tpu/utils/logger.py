"""Structured logging: JSON-to-file + ANSI console, request-id correlation.

Capability parity with the reference logger (app/utils/logger.py:19-91 for
the two formatters, :16/:37-39/:81-83 for the ContextVar request-id
correlation, :178-240 for the domain helpers), rebuilt around a single
module-level registry so every subsystem shares one configuration.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from contextvars import ContextVar
from typing import Any

request_id_var: ContextVar[str | None] = ContextVar("request_id", default=None)

_ANSI = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[35m",
}
_RESET = "\033[0m"


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry: dict[str, Any] = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        rid = request_id_var.get()
        if rid:
            entry["request_id"] = rid
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        extra = getattr(record, "extra_fields", None)
        if extra:
            entry.update(extra)
        return json.dumps(entry, ensure_ascii=False, default=str)


class ConsoleFormatter(logging.Formatter):
    def __init__(self, color: bool = True):
        super().__init__()
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        rid = request_id_var.get()
        rid_part = f" [{rid[:8]}]" if rid else ""
        level = record.levelname
        if self.color:
            level = f"{_ANSI.get(level, '')}{level:<8}{_RESET}"
        else:
            level = f"{level:<8}"
        msg = f"{ts} {level} {record.name}{rid_part}: {record.getMessage()}"
        if record.exc_info:
            msg += "\n" + self.formatException(record.exc_info)
        return msg


_configured = False


def configure_logging(level: str = "INFO", log_path: str | None = None,
                      console: bool = True,
                      json_console: bool | None = None) -> None:
    """Install handlers on the ``fasttalk`` root logger (idempotent).

    ``json_console`` switches the console stream to structured JSON
    lines (one object per record, request-id correlated) — for
    deployments whose log shipper wants machine-parseable stderr.
    Defaults from ``LOG_FORMAT=json``; the ANSI console otherwise.
    """
    global _configured
    if json_console is None:
        json_console = os.getenv("LOG_FORMAT", "").strip().lower() in (
            "json", "jsonl", "structured")
    root = logging.getLogger("fasttalk")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.handlers.clear()
    root.propagate = False
    if console:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(JsonFormatter() if json_console
                       else ConsoleFormatter(color=sys.stderr.isatty()))
        root.addHandler(h)
    if log_path:
        os.makedirs(log_path, exist_ok=True)
        fh = logging.FileHandler(os.path.join(log_path, "fasttalk.jsonl"))
        fh.setFormatter(JsonFormatter())
        root.addHandler(fh)
    _configured = True


def get_logger(name: str) -> "StructuredLogger":
    if not _configured:
        configure_logging(os.getenv("LOG_LEVEL", "INFO"))
    return StructuredLogger(logging.getLogger(f"fasttalk.{name}"))


class StructuredLogger:
    """Thin wrapper adding structured-extra and domain log helpers."""

    def __init__(self, logger: logging.Logger):
        self._logger = logger

    def _log(self, level: int, msg: str, exc_info: bool = False, **extra: Any) -> None:
        self._logger.log(level, msg, exc_info=exc_info,
                         extra={"extra_fields": extra} if extra else None)

    def debug(self, msg: str, **extra: Any) -> None:
        self._log(logging.DEBUG, msg, **extra)

    def info(self, msg: str, **extra: Any) -> None:
        self._log(logging.INFO, msg, **extra)

    def warning(self, msg: str, **extra: Any) -> None:
        self._log(logging.WARNING, msg, **extra)

    def error(self, msg: str, exc_info: bool = False, **extra: Any) -> None:
        self._log(logging.ERROR, msg, exc_info=exc_info, **extra)

    def critical(self, msg: str, exc_info: bool = False, **extra: Any) -> None:
        self._log(logging.CRITICAL, msg, exc_info=exc_info, **extra)

    # Domain helpers (reference: logger.py:178-240) — true token counts here,
    # since this framework owns the tokenizer.
    def log_generation(self, session_id: str, tokens: int, duration_s: float,
                       ttft_ms: float | None = None, **extra: Any) -> None:
        tok_s = tokens / duration_s if duration_s > 0 else 0.0
        self.info(
            f"[{session_id}] generated {tokens} tok in {duration_s:.2f}s ({tok_s:.1f} tok/s)",
            session_id=session_id, tokens=tokens, duration_s=duration_s,
            tokens_per_second=tok_s, ttft_ms=ttft_ms, **extra)

    def log_connection(self, session_id: str, event: str,
                       level: str = "info", **extra: Any) -> None:
        # Per-connection close lines are DEBUG at the call site: at 16+
        # concurrent bench sessions the INFO tail was nothing but
        # "connection closed" lines burying the throughput summary.
        getattr(self, level, self.info)(
            f"[{session_id}] connection {event}", session_id=session_id,
            event=event, **extra)

    def log_performance(self, name: str, duration_ms: float, **extra: Any) -> None:
        self.debug(f"perf {name}: {duration_ms:.1f}ms", perf=name,
                   duration_ms=duration_ms, **extra)
