"""Error taxonomy, circuit breaker, and retry — wired into the serving path.

Capability parity with the reference error handler (app/utils/error_handler.py:
18-76 taxonomy, :79-213 CircuitBreaker, :216-264 RetryManager, :349-400
ErrorHandler), with two deliberate fixes over the reference:
(1) the breaker and retry manager are actually used around engine calls
    (the reference constructed them at error_handler.py:285-294 and never
    wired them — SURVEY.md §5), and
(2) all state is safe to touch from asyncio + the engine thread.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, TypeVar

T = TypeVar("T")


class ErrorCategory(str, Enum):
    CONNECTION = "connection_error"
    TIMEOUT = "timeout_error"
    MODEL = "model_error"
    VALIDATION = "validation_error"
    RATE_LIMIT = "rate_limit_error"
    RESOURCE = "resource_error"
    CANCELLED = "cancelled"
    INTERNAL = "internal_error"


class ErrorSeverity(str, Enum):
    LOW = "low"
    MEDIUM = "medium"
    HIGH = "high"
    CRITICAL = "critical"


class LLMServiceError(Exception):
    """Service error carrying category/severity/recoverability hints that are
    surfaced to WebSocket clients (reference: error_handler.py:50-76)."""

    def __init__(self, message: str,
                 category: ErrorCategory = ErrorCategory.INTERNAL,
                 severity: ErrorSeverity = ErrorSeverity.MEDIUM,
                 recoverable: bool = True,
                 retry_after: float | None = None,
                 details: dict[str, Any] | None = None):
        super().__init__(message)
        self.message = message
        self.category = category
        self.severity = severity
        self.recoverable = recoverable
        self.retry_after = retry_after
        self.details = details or {}

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "code": self.category.value,
            "message": self.message,
            "severity": self.severity.value,
            "recoverable": self.recoverable,
        }
        if self.retry_after is not None:
            d["retry_after"] = self.retry_after
        if self.details:
            d["details"] = self.details
        return d


class AdmissionRejected(LLMServiceError):
    """Submission shed at admission (scheduling/scheduler.py): queue at
    bound, estimated wait past the deadline, upstream saturation, or a
    draining server. Always recoverable and always carries a computed
    ``retry_after`` — the WS error frame includes it via to_dict() and
    the OpenAI-compatible route maps it to 429 + Retry-After. Kept as
    its own type so the serving layer can tell load shedding (client
    should back off; NOT a backend failure, must not trip the circuit
    breaker) from genuine engine errors."""

    def __init__(self, message: str, retry_after: float,
                 reason: str = "shed"):
        super().__init__(message, category=ErrorCategory.RATE_LIMIT,
                         severity=ErrorSeverity.MEDIUM, recoverable=True,
                         retry_after=retry_after,
                         details={"reason": reason})
        self.reason = reason

    @classmethod
    def from_shed_event(cls, event: dict) -> "AdmissionRejected":
        """Rebuild from an engine terminal error event whose ``code``
        is in ``ENGINE_SHED_CODES`` (queue-deadline expiry, paged-KV
        block-pool exhaustion) — one definition of the message
        fallback and retry_after coercion for every serving surface;
        the event's code rides through as ``details.reason``."""
        return cls(str(event.get("error") or "request shed"),
                   retry_after=float(event.get("retry_after") or 1.0),
                   reason=str(event.get("code") or "shed"))


# Engine terminal-error codes that are LOAD SHEDDING, not backend
# faults: every serving surface maps them to the rate-limit taxonomy
# (WS frame / SSE payload with retry_after, HTTP 429) and leaves the
# circuit breaker untouched — a shed is the engine protecting itself.
ENGINE_SHED_CODES = ("deadline_expired", "kv_blocks_exhausted")


class CircuitState(str, Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreakerOpen(LLMServiceError):
    def __init__(self, retry_after: float):
        super().__init__(
            "Service temporarily unavailable (circuit open)",
            category=ErrorCategory.RESOURCE, severity=ErrorSeverity.HIGH,
            recoverable=True, retry_after=retry_after)


class CircuitBreaker:
    """CLOSED → OPEN after ``failure_threshold`` consecutive failures;
    OPEN → HALF_OPEN after ``reset_timeout``; HALF_OPEN closes after
    ``half_open_successes`` successes or re-opens on any failure."""

    def __init__(self, failure_threshold: int = 5, reset_timeout: float = 30.0,
                 half_open_successes: int = 2):
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.half_open_successes = half_open_successes
        self._state = CircuitState.CLOSED
        self._failures = 0
        self._successes = 0
        self._opened_at = 0.0
        self._lock = threading.Lock()

    @property
    def state(self) -> CircuitState:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (self._state is CircuitState.OPEN
                and time.monotonic() - self._opened_at >= self.reset_timeout):
            self._state = CircuitState.HALF_OPEN
            self._successes = 0

    def check(self) -> None:
        """Raise CircuitBreakerOpen if calls must not proceed."""
        with self._lock:
            self._maybe_half_open()
            if self._state is CircuitState.OPEN:
                remaining = self.reset_timeout - (time.monotonic() - self._opened_at)
                raise CircuitBreakerOpen(retry_after=max(0.0, remaining))

    def record_success(self) -> None:
        with self._lock:
            if self._state is CircuitState.HALF_OPEN:
                self._successes += 1
                if self._successes >= self.half_open_successes:
                    self._state = CircuitState.CLOSED
                    self._failures = 0
            else:
                self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            if self._state is CircuitState.HALF_OPEN:
                self._state = CircuitState.OPEN
                self._opened_at = time.monotonic()
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._state = CircuitState.OPEN
                self._opened_at = time.monotonic()

    def to_dict(self) -> dict[str, Any]:
        with self._lock:
            self._maybe_half_open()
            return {"state": self._state.value, "failures": self._failures}


class RetryManager:
    """Exponential backoff with jitter (reference: error_handler.py:216-264)."""

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.5,
                 max_delay: float = 10.0, jitter: float = 0.25):
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter

    def delay_for(self, attempt: int) -> float:
        d = min(self.max_delay, self.base_delay * (2 ** attempt))
        return d * (1.0 + random.uniform(-self.jitter, self.jitter))

    def retry_with_backoff(self, fn: Callable[[], T],
                           retryable: tuple[type[BaseException], ...] = (Exception,),
                           on_retry: Callable[[int, BaseException], None] | None = None,
                           ) -> T:
        last: BaseException | None = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retryable as e:  # noqa: PERF203
                if isinstance(e, LLMServiceError) and not e.recoverable:
                    raise
                last = e
                if attempt + 1 < self.max_attempts:
                    if on_retry:
                        on_retry(attempt, e)
                    time.sleep(self.delay_for(attempt))
        assert last is not None
        raise last


@dataclass
class ErrorRecord:
    ts: float
    category: str
    severity: str
    message: str
    context: dict[str, Any] = field(default_factory=dict)


class ErrorHandler:
    """Categorizes foreign exceptions and keeps a bounded history
    (reference: error_handler.py:349-400)."""

    _PATTERNS: list[tuple[tuple[str, ...], ErrorCategory, ErrorSeverity]] = [
        (("connection", "refused", "unreachable", "reset by peer"),
         ErrorCategory.CONNECTION, ErrorSeverity.HIGH),
        (("timeout", "timed out", "deadline"),
         ErrorCategory.TIMEOUT, ErrorSeverity.MEDIUM),
        (("out of memory", "oom", "resource exhausted", "hbm"),
         ErrorCategory.RESOURCE, ErrorSeverity.CRITICAL),
        (("rate limit", "too many requests"),
         ErrorCategory.RATE_LIMIT, ErrorSeverity.MEDIUM),
        (("invalid", "validation", "must be", "expected"),
         ErrorCategory.VALIDATION, ErrorSeverity.LOW),
        (("cancel",), ErrorCategory.CANCELLED, ErrorSeverity.LOW),
    ]

    def __init__(self, history_size: int = 200):
        self._history: deque[ErrorRecord] = deque(maxlen=history_size)
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def handle_error(self, exc: BaseException,
                     context: dict[str, Any] | None = None) -> LLMServiceError:
        if isinstance(exc, LLMServiceError):
            err = exc
        else:
            text = str(exc).lower()
            category, severity = ErrorCategory.INTERNAL, ErrorSeverity.MEDIUM
            for needles, cat, sev in self._PATTERNS:
                if any(n in text for n in needles):
                    category, severity = cat, sev
                    break
            err = LLMServiceError(str(exc) or type(exc).__name__,
                                  category=category, severity=severity)
        with self._lock:
            self._history.append(ErrorRecord(
                ts=time.time(), category=err.category.value,
                severity=err.severity.value, message=err.message,
                context=context or {}))
            self._counts[err.category.value] = self._counts.get(err.category.value, 0) + 1
        return err

    def get_error_stats(self) -> dict[str, Any]:
        with self._lock:
            recent = [
                {"ts": r.ts, "category": r.category, "severity": r.severity,
                 "message": r.message}
                for r in list(self._history)[-10:]
            ]
            return {
                "total_errors": sum(self._counts.values()),
                "by_category": dict(self._counts),
                "recent": recent,
            }
