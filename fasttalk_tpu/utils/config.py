"""Environment-driven configuration with first-class TPU device selection.

Parity surface: mirrors the reference config fields and env-var names
(reference: app/utils/config.py:63-158) so existing ``.env`` files keep
working, and adds the ``tpu`` branch the reference lacked
(reference: app/utils/config.py:17-60 only knew cuda|cpu|mps) plus the
engine-tuning knobs that used to live in the external vLLM container's
flags (reference: docker-compose.vllm.yml:38-53, .env.vllm.example:32-47).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Any

VALID_DEVICES = ("tpu", "cuda", "cpu", "mps")
VALID_PROVIDERS = ("tpu", "vllm", "ollama", "openai", "fake")


def _env_str(name: str, default: str) -> str:
    return os.getenv(name, default)


def _env_int(name: str, default: int) -> int:
    raw = os.getenv(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"env {name} must be an integer, got {raw!r}") from None


def _env_float(name: str, default: float) -> float:
    raw = os.getenv(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"env {name} must be a number, got {raw!r}") from None


def _env_bool(name: str, default: bool) -> bool:
    return os.getenv(name, "true" if default else "false").strip().lower() in (
        "1", "true", "yes", "on",
    )


def detect_compute_device() -> str:
    """Resolve COMPUTE_DEVICE with availability checking and fallback.

    Order: explicit ``COMPUTE_DEVICE`` env (validated against what is
    actually available) → auto-detect tpu → cuda → mps → cpu.
    TPU availability is probed via ``jax.devices()`` so a machine with
    libtpu but no attached chips still falls back cleanly.
    """
    requested = os.getenv("COMPUTE_DEVICE", "").strip().lower()
    if requested and requested not in VALID_DEVICES:
        requested = ""

    available = _available_devices()
    if requested:
        if requested in available:
            return requested
        # Requested device unavailable: fall through to best available.
    for dev in ("tpu", "cuda", "mps", "cpu"):
        if dev in available:
            return dev
    return "cpu"


def _available_devices() -> set[str]:
    found: set[str] = {"cpu"}
    try:  # TPU via JAX — the first-class path.
        import jax

        platforms = {d.platform.lower() for d in jax.devices()}
        if platforms & {"tpu", "axon"}:
            found.add("tpu")
        if "gpu" in platforms or "cuda" in platforms:
            found.add("cuda")
    except Exception:
        pass
    try:  # torch backends kept for reference back-compat (cuda/mps boxes).
        import torch

        if torch.cuda.is_available():
            found.add("cuda")
        if getattr(torch.backends, "mps", None) and torch.backends.mps.is_available():
            found.add("mps")
    except Exception:
        pass
    return found


@dataclass
class Config:
    """All service settings, each overridable via environment variable.

    Reference parity: field/env names follow app/utils/config.py:63-158;
    new TPU-engine fields are grouped at the bottom.
    """

    # Compute device — now including "tpu" (the north-star change).
    compute_device: str = field(default_factory=detect_compute_device)

    # Provider: "tpu" (in-tree JAX engine), or legacy "vllm"/"ollama" HTTP
    # passthrough for back-compat (reference: config.py:81).
    llm_provider: str = field(default_factory=lambda: _env_str("LLM_PROVIDER", "tpu"))

    # Model
    model_name: str = field(default_factory=lambda: _env_str("LLM_MODEL", "llama3.2:1b"))
    model_path: str = field(default_factory=lambda: _env_str("MODEL_PATH", "/app/models"))
    tokenizer_path: str = field(default_factory=lambda: _env_str("TOKENIZER_PATH", ""))

    # Legacy backend endpoints (reference: config.py:96-120) — retained so
    # the provider=vllm/ollama back-compat handlers keep working.
    vllm_base_url: str = field(
        default_factory=lambda: _env_str("VLLM_BASE_URL", "http://vllm:8000/v1"))
    vllm_model: str = field(
        default_factory=lambda: _env_str(
            "VLLM_MODEL", "hugging-quants/Meta-Llama-3.1-8B-Instruct-AWQ-INT4"))
    vllm_api_key: str = field(default_factory=lambda: _env_str("VLLM_API_KEY", "not-needed"))
    vllm_timeout: float = field(default_factory=lambda: _env_float("VLLM_TIMEOUT", 600.0))
    ollama_base_url: str = field(
        default_factory=lambda: _env_str("OLLAMA_BASE_URL", "http://ollama:11434"))
    ollama_keep_alive: str = field(default_factory=lambda: _env_str("OLLAMA_KEEP_ALIVE", "5m"))
    ollama_timeout: float = field(default_factory=lambda: _env_float("OLLAMA_TIMEOUT", 600.0))

    # Agent / tools (reference: config.py:102-111)
    enable_agent: bool = field(default_factory=lambda: _env_bool("ENABLE_PYDANTIC_AI", True))
    enable_web_search: bool = field(default_factory=lambda: _env_bool("ENABLE_WEB_SEARCH", True))
    enable_tools: bool = field(default_factory=lambda: _env_bool("ENABLE_TOOLS", True))
    web_search_rate_limit: float = field(
        default_factory=lambda: _env_float("DUCKDUCKGO_RATE_LIMIT", 1.0))
    # auto = live DuckDuckGo with offline fallback; duckduckgo; offline
    web_search_backend: str = field(
        default_factory=lambda: _env_str("WEB_SEARCH_BACKEND", "auto"))
    web_search_timeout: float = field(
        default_factory=lambda: _env_float("WEB_SEARCH_TIMEOUT", 10.0))
    system_prompt: str = field(default_factory=lambda: _env_str(
        "SYSTEM_PROMPT",
        "You are a helpful voice assistant. Keep responses concise and conversational."))

    # Generation defaults (reference: config.py:122-128)
    default_temperature: float = field(
        default_factory=lambda: _env_float("DEFAULT_TEMPERATURE", 0.7))
    default_max_tokens: int = field(default_factory=lambda: _env_int("DEFAULT_MAX_TOKENS", 2048))
    default_context_window: int = field(
        default_factory=lambda: _env_int("DEFAULT_CONTEXT_WINDOW", 8192))
    default_top_p: float = field(default_factory=lambda: _env_float("DEFAULT_TOP_P", 0.9))
    default_top_k: int = field(default_factory=lambda: _env_int("DEFAULT_TOP_K", 40))
    # Unset resolves per provider in __post_init__: 1.1 for the in-tree
    # engine and Ollama (the engine-side default the reference silently
    # relied on — its gateway never set a penalty, but the Ollama engine
    # applied ~1.1 to every generation, reference app/core/
    # ollama_handler.py:144-162); 1.0 for vllm (vLLM's own default —
    # and strict OpenAI-compatible backends 400 on the non-standard
    # repetition_penalty param, so it must not be emitted by default).
    default_repeat_penalty: float = field(
        default_factory=lambda: _env_float("DEFAULT_REPEAT_PENALTY", -1.0))
    default_presence_penalty: float = field(
        default_factory=lambda: _env_float("DEFAULT_PRESENCE_PENALTY", 0.0))
    default_frequency_penalty: float = field(
        default_factory=lambda: _env_float("DEFAULT_FREQUENCY_PENALTY", 0.0))

    # Server (reference: config.py:130-136)
    host: str = field(default_factory=lambda: _env_str("LLM_HOST", "0.0.0.0"))
    port: int = field(default_factory=lambda: _env_int("LLM_PORT", 8000))
    max_connections: int = field(default_factory=lambda: _env_int("LLM_MAX_CONNECTIONS", 50))
    log_level: str = field(default_factory=lambda: _env_str("LOG_LEVEL", "INFO"))

    # Monitoring (reference: config.py:138-142)
    monitoring_port: int = field(default_factory=lambda: _env_int("LLM_MONITORING_PORT", 9092))
    monitoring_host: str = field(
        default_factory=lambda: _env_str("LLM_MONITORING_HOST", "0.0.0.0"))

    # Session (reference: config.py:149-152)
    session_timeout: int = field(default_factory=lambda: _env_int("SESSION_TIMEOUT", 3600))
    # Supervised in-process engine restart after a crash (the in-tree
    # analogue of the reference's docker `restart: unless-stopped`).
    engine_auto_restart: bool = field(
        default_factory=lambda: _env_bool("ENGINE_AUTO_RESTART", True))
    # Restart-storm guard (serving/launcher.py RestartBudget, docs/
    # RESILIENCE.md): at most max restarts per rolling window, with
    # exponential backoff from backoff_s (capped at 60 s) between
    # attempts. On exhaustion the supervisor stops resurrecting and
    # /health reports dead — a persistently poisoned device state
    # must not crash-loop at full CPU.
    supervisor_max_restarts: int = field(
        default_factory=lambda: _env_int("SUPERVISOR_MAX_RESTARTS", 5))
    supervisor_window_s: float = field(
        default_factory=lambda: _env_float("SUPERVISOR_WINDOW_S",
                                           300.0))
    supervisor_backoff_s: float = field(
        default_factory=lambda: _env_float("SUPERVISOR_BACKOFF_S", 2.0))
    # ---- Fault injection (fasttalk_tpu/resilience/failpoints.py,
    # docs/RESILIENCE.md). FAULT_POINTS is a validated spec of named
    # failpoints to arm, e.g.
    # "engine.decode.dispatch=error;count=1,kv.park.copy=delay_ms:250"
    # — unset (the default) compiles the whole subsystem down to one
    # module-flag check per seam (measured <1% tok/s,
    # BENCH_MODE=chaos). FAULT_HTTP gates the runtime
    # POST /debug/fault endpoint on the monitoring port: OFF by
    # default — never enable it in production. ----
    fault_points: str = field(
        default_factory=lambda: _env_str("FAULT_POINTS", ""))
    fault_http_enabled: bool = field(
        default_factory=lambda: _env_bool("FAULT_HTTP", False))
    max_history_length: int = field(default_factory=lambda: _env_int("MAX_HISTORY_LENGTH", 50))
    log_path: str = field(default_factory=lambda: _env_str("LOG_PATH", "./logs"))

    # ---- TPU engine knobs (replace the external engine's flag surface:
    # VLLM_MAX_NUM_SEQS / VLLM_MAX_NUM_BATCHED_TOKENS / GPU_MEMORY_UTILIZATION
    # at .env.vllm.example:32-47) ----
    decode_slots: int = field(default_factory=lambda: _env_int("TPU_DECODE_SLOTS", 16))
    max_model_len: int = field(default_factory=lambda: _env_int("TPU_MAX_MODEL_LEN", 8192))
    prefill_chunk: int = field(default_factory=lambda: _env_int("TPU_PREFILL_CHUNK", 512))
    dtype: str = field(default_factory=lambda: _env_str("TPU_DTYPE", "bfloat16"))
    tp_size: int = field(default_factory=lambda: _env_int("TPU_TP_SIZE", 1))
    dp_size: int = field(default_factory=lambda: _env_int("TPU_DP_SIZE", 1))
    # Sequence-parallel axis: shards each slot's KV over sp chips.
    # Long fresh prompts prefill through ring attention and decode
    # attends via the sharded flash-decoding combine — per-chip serving
    # memory O(T/sp) (parallel/ring_attention.py).
    sp_size: int = field(default_factory=lambda: _env_int("TPU_SP_SIZE", 1))
    # Multi-host SPMD serving role (parallel/spmd_serving.py):
    # "off" | "leader" (serves the gateway; publishes every device call
    # to followers over TPU_SPMD_ADDR) | "follower" (replays the
    # leader's calls against this host's shards; no gateway). Requires
    # the usual jax.distributed env (TPU_COORDINATOR_ADDR,
    # TPU_NUM_PROCESSES, TPU_PROCESS_ID) for the device cluster itself.
    spmd_role: str = field(
        default_factory=lambda: _env_str("TPU_SPMD_ROLE", "off"))
    spmd_addr: str = field(
        default_factory=lambda: _env_str("TPU_SPMD_ADDR",
                                         "127.0.0.1:8890"))
    spmd_followers: int = field(
        default_factory=lambda: _env_int("TPU_SPMD_FOLLOWERS", 1))
    # SPMD cluster liveness (parallel/spmd_serving.py, docs/
    # RESILIENCE.md): the leader heartbeats followers every interval
    # (0 disables the beacon), and a follower treats a leader silent
    # past the timeout as dead (ConnectionError + exit for a cluster
    # restart) instead of blocking in recv until a collective times
    # out.
    spmd_hb_interval_s: float = field(
        default_factory=lambda: _env_float("SPMD_HB_INTERVAL_S", 2.0))
    spmd_hb_timeout_s: float = field(
        default_factory=lambda: _env_float("SPMD_HB_TIMEOUT_S", 8.0))
    hbm_util: float = field(default_factory=lambda: _env_float("TPU_HBM_UTILIZATION", 0.9))
    # The length-pruning Pallas decode-attention kernel (ops/
    # pallas_attention.py). Rides the scatter decode path and composes
    # with KV_QUANT=int8 (fused in-kernel dequant), KV_LAYOUT=paged
    # (block-walking variant), speculative decoding (multi-token verify
    # blocks) and structured decoding. Off by default: profiled on
    # v5e-1 the original q_len=1 bf16 variant's per-grid-cell cost (8
    # statically unrolled tiny GQA matmuls) made it ~2x SLOWER than the
    # XLA attention over a bucketed view at chat-scale lengths — it was
    # the hidden reason r2's int8 measured equal to bf16. Wins where
    # block-level pruning beats reading the whole bucket (long buckets,
    # short active lengths) and on the int8 tier, where it skips the
    # materialised bf16 dequant buffer; see docs/ROOFLINE.md for the
    # measured decision table per config.
    use_pallas_attention: bool = field(
        default_factory=lambda: _env_bool("TPU_USE_PALLAS_ATTENTION", False))
    # Int8 dequant-fused matmul kernel (single-device decode); gates
    # independently of the attention kernel.
    use_pallas_int8: bool = field(
        default_factory=lambda: _env_bool("TPU_USE_PALLAS_INT8", True))
    # Tokens decoded per device call (lax.scan inside one jitted step) and
    # number of calls kept in flight. Together these amortise and overlap
    # per-call host/dispatch latency — the dominant cost when the chip is
    # reached over a relay, and still a measurable one locally. 32:
    # donated-buffer aliasing is unavailable on the relayed attach path
    # (measured: a 1-element update of a donated 1 GiB cache costs a
    # full-buffer copy), so every decode call pays a KV-cache
    # boundary copy — more steps per call amortise it. Cost: cancel
    # granularity coarsens to one call (~130 ms at 32 steps).
    decode_steps_per_call: int = field(
        default_factory=lambda: _env_int("TPU_DECODE_STEPS", 32))
    # At 32 steps/call one call's compute already covers the token-fetch
    # round trip, so depth 2 reaches full throughput while keeping the
    # stale-call tail (which delays the NEXT request's first token on the
    # in-order device queue) as short as possible.
    pipeline_depth: int = field(
        default_factory=lambda: _env_int("TPU_PIPELINE_DEPTH", 2))
    # Cross-session shared-prefix KV: a fresh session whose prompt
    # starts with rows resident in another slot (common system prompt)
    # gets them by device copy instead of re-prefill — cuts TTFT and
    # prefill load at high concurrency (single-device path).
    shared_prefix: bool = field(
        default_factory=lambda: _env_bool("TPU_SHARED_PREFIX", True))
    # Speculative decoding: "off" | "ngram" | "auto". "ngram" is the
    # always-on self-drafting prompt-lookup (draft from the slot's own
    # token history on-device, verify draft+1 positions in one
    # scatter-decode block, accept the longest sampled-equal prefix;
    # exactly distribution-preserving, see engine/engine.py
    # _get_spec_decode_fn) — worthwhile on repetitive/structured text,
    # a measured ~25% regression on incompressible sampled text
    # (docs/SPEC_DECODE.md). "auto" (default) makes that call per
    # decode call from the engine's own measured acceptance EMA vs the
    # break-even (TPU_SPEC_BREAKEVEN, default 1.45 plain-step
    # equivalents per verify block), probing periodically — no knob
    # guessing, bounded downside (~1 probe call in 16). Single-device
    # scatter path only; the mesh path always decodes plain.
    spec_decode: str = field(
        default_factory=lambda: _env_str("TPU_SPEC_DECODE", "auto"))
    # Draft tokens proposed per verify block (block = draft + 1).
    spec_draft_len: int = field(
        default_factory=lambda: _env_int("TPU_SPEC_DRAFT", 7))
    # Auto-mode enable threshold: EMA tokens-per-verify-block above
    # which speculative calls win (a verify block costs ~1.43 plain
    # steps on v5e — docs/SPEC_DECODE.md).
    spec_breakeven: float = field(
        default_factory=lambda: _env_float("TPU_SPEC_BREAKEVEN", 1.45))
    # Token sampling candidate preselection: "fast" (block-max, the
    # approx_max_k algorithm — greedy rows stay exact, measured 2.4x
    # cheaper than the full-vocab sort which was ~54% of a decode step)
    # or "exact" (full-vocab lax.top_k).
    sampling: str = field(
        default_factory=lambda: _env_str("TPU_SAMPLING", "fast"))
    # Weight quantization for serving: "none" | "int8" (per-output-channel
    # symmetric, in-tree replacement for the reference's external AWQ
    # engine config, .env.vllm.example:21) | "int4". Legacy alias of
    # WEIGHT_QUANT below — __post_init__ resolves the two into
    # agreement, and setting both to different tiers is a named startup
    # error.
    quantize: str = field(default_factory=lambda: _env_str("TPU_QUANTIZE", "none"))
    # ---- Int4 weight tier (fasttalk_tpu/quantization/,
    # docs/QUANTIZATION.md) ----
    # Serving weight tier: "" (unset -> resolved from TPU_QUANTIZE) |
    # "off" | "int8" | "int4" (group-wise symmetric 4-bit, nibble-
    # packed; the embedding/lm_head stay per-row int8 — the gather and
    # the streaming head kernel want per-row scales).
    weight_quant: str = field(
        default_factory=lambda: _env_str("WEIGHT_QUANT", ""))
    # Contraction rows sharing one int4 scale. Must be even (the nibble
    # packing pairs adjacent rows, and a scale group must never split a
    # pair); that it divides every matmul contraction dim of the model
    # is validated at engine build (quantization/int4.py
    # validate_group).
    weight_quant_group: int = field(
        default_factory=lambda: _env_int("WEIGHT_QUANT_GROUP", 128))
    # AWQ calibration source for scripts/quantize_checkpoint.py: ""
    # (data-free max-abs), "corpus" (the in-tree tinychat corpus), or a
    # path to a text file with one prompt per line. The serving path
    # never calibrates inline — it picks up the prepared cache the CLI
    # writes.
    weight_quant_calib: str = field(
        default_factory=lambda: _env_str("WEIGHT_QUANT_CALIB", ""))
    # Int4 dequant-fused Pallas matmul (single-device T=1 decode,
    # requires WEIGHT_QUANT=int4; ops/pallas_int8.py int4_matmul). Off
    # by default pending on-device benchmarking against the XLA
    # unpack+dequant path, which is always available.
    use_pallas_int4: bool = field(
        default_factory=lambda: _env_bool("TPU_USE_PALLAS_INT4", False))
    # Persistent XLA compilation cache: "" = on at the default location
    # (MODEL_PATH/.xla_cache or a per-user tmp dir), a path = on there,
    # "off" = disabled. Makes warmup a one-time cost per configuration
    # instead of per process (utils/compile_cache.py).
    compile_cache: str = field(
        default_factory=lambda: _env_str("TPU_COMPILE_CACHE", ""))
    # ---- Admission control / request scheduling (scheduling/
    # scheduler.py, docs/SCHEDULING.md) ----
    # Bound on requests waiting for a decode slot; the excess is shed
    # immediately with a retry_after hint instead of queueing to
    # time out.
    sched_queue_bound: int = field(
        default_factory=lambda: _env_int("SCHED_QUEUE_BOUND", 256))
    # Default queue TTL: a request still waiting past this is expired
    # with a terminal event before it ever touches the TPU. Clients
    # may override per session/request via the "deadline_s" config key.
    sched_default_deadline_s: float = field(
        default_factory=lambda: _env_float("SCHED_DEADLINE_S", 30.0))
    # Priority class when the client sets none: "interactive" admits
    # before "bulk" (clients override via the "priority" config key).
    sched_default_priority: str = field(
        default_factory=lambda: _env_str("SCHED_DEFAULT_PRIORITY",
                                         "interactive"))
    # Starvation guard: a bulk request whose queue wait exceeds this
    # is promoted ahead of interactive work for one admission.
    sched_bulk_aging_s: float = field(
        default_factory=lambda: _env_float("SCHED_AGING_S", 5.0))
    # Graceful drain: how long server shutdown waits for in-flight and
    # queued requests to finish before cancelling the stragglers.
    sched_drain_timeout_s: float = field(
        default_factory=lambda: _env_float("SCHED_DRAIN_TIMEOUT_S", 30.0))
    # Remote providers (vllm/ollama/openai): cap on concurrent upstream
    # requests, so backpressure/shedding applies on the remote branch
    # too (waiters past the admission deadline shed with retry_after).
    remote_max_inflight: int = field(
        default_factory=lambda: _env_int("REMOTE_MAX_INFLIGHT", 32))
    # Bounded jittered retries for idempotent (pre-first-token) remote
    # upstream failures — connect errors and 5xx before any output.
    # 0 disables (first failure surfaces immediately).
    remote_connect_retries: int = field(
        default_factory=lambda: _env_int("REMOTE_CONNECT_RETRIES", 2))
    # ---- Fleet router (fasttalk_tpu/router/, docs/ROUTER.md) ----
    # Front a fleet of engine replicas behind this server instead of a
    # single engine: session-affinity routing, health probes, failover
    # with mid-stream resume, coordinated drain.
    router_enabled: bool = field(
        default_factory=lambda: _env_bool("ROUTER_ENABLED", False))
    # In-process engine replicas the router builds (each a full engine
    # instance: CPU fleets for test/bench, dp-style multi-engine on
    # real hardware). May be 0 when ROUTER_BACKENDS supplies the fleet.
    fleet_replicas: int = field(
        default_factory=lambda: _env_int("FLEET_REPLICAS", 2))
    # Comma-separated serving roots of remote FastTalk replicas
    # (e.g. "http://replica-1:8000,http://replica-2:8000"): generations
    # go through their /v1 surface via the existing remote.py client;
    # probes read their /health body.
    router_backends: str = field(
        default_factory=lambda: _env_str("ROUTER_BACKENDS", ""))
    # Health/load probe cadence (seconds); 0 disables the probe thread
    # (probes then only run on demand — tests).
    router_probe_interval_s: float = field(
        default_factory=lambda: _env_float("ROUTER_PROBE_INTERVAL_S",
                                           2.0))
    # How long an idle session stays pinned to its replica. Default
    # matches KV_PARK_TTL_S: once the parked KV has expired server-side
    # there is nothing left to be sticky to.
    router_affinity_ttl_s: float = field(
        default_factory=lambda: _env_float("ROUTER_AFFINITY_TTL_S",
                                           600.0))
    # Replica failures one request will route around before giving up.
    router_failover_retries: int = field(
        default_factory=lambda: _env_int("ROUTER_FAILOVER_RETRIES", 2))
    # Resume mid-stream failovers on a survivor (re-prefill from the
    # transcript; client sees a `resumed` event). Off = mid-stream
    # replica death surfaces as a terminal error instead.
    router_resume: bool = field(
        default_factory=lambda: _env_bool("ROUTER_RESUME", True))
    # Consecutive failed probes before a replica is marked dead (a
    # stream failing while the backend is unreachable marks it dead
    # immediately, independent of this).
    router_dead_probes: int = field(
        default_factory=lambda: _env_int("ROUTER_DEAD_PROBES", 2))
    # ---- Fleet session fabric (docs/ROUTER.md "Cross-replica KV
    # migration" / "Elastic replicas") ----
    # Move parked session KV between replicas on drain/failover so the
    # next turn RESTORES on the target instead of re-prefilling the
    # transcript. Off = the pre-fabric behaviour (drain releases the
    # entry, failover re-prefills).
    router_migrate: bool = field(
        default_factory=lambda: _env_bool("ROUTER_MIGRATE", True))
    # Hard bound on one migration transfer (export + wire + import).
    # A hung channel falls back to re-prefill — it must never wedge a
    # drain or a failover.
    router_migrate_timeout_s: float = field(
        default_factory=lambda: _env_float("ROUTER_MIGRATE_TIMEOUT_S",
                                           10.0))
    # Serve the /kv/parked/{session_id} migration endpoints on THIS
    # replica's serving port. Off by default: the port is
    # unauthenticated, and the channel exposes parked transcripts
    # (read), pool writes, and purges. Enable ONLY on replicas whose
    # serving port is reachable solely from the router network —
    # a remote router needs it to migrate KV in and out; in-process
    # fleets hand entries over directly and never need it.
    kv_migrate_http: bool = field(
        default_factory=lambda: _env_bool("KV_MIGRATE_HTTP", False))
    # Co-locate sessions sharing a system prompt on the replica that
    # already serves that prefix (hits the shared-prefix stamp /
    # paged block aliasing) while its load is within one queued
    # request of the best candidate.
    router_prefix_affinity: bool = field(
        default_factory=lambda: _env_bool("ROUTER_PREFIX_AFFINITY",
                                          True))
    # Elastic replica scaling (router/elastic.py). FLEET_SCALE_MAX=0
    # disables the scaler (fixed fleet); > 0 lets the launcher grow
    # the in-process fleet up to this size on queue depth / SLO burn
    # and shrink it back to FLEET_SCALE_MIN via client-invisible
    # drain-then-migrate after sustained idleness.
    fleet_scale_min: int = field(
        default_factory=lambda: _env_int("FLEET_SCALE_MIN", 1))
    fleet_scale_max: int = field(
        default_factory=lambda: _env_int("FLEET_SCALE_MAX", 0))
    # Aggregate queued requests across the fleet that trigger a
    # scale-up (an SLO page-burn triggers one regardless of depth).
    fleet_scale_up_queue: int = field(
        default_factory=lambda: _env_int("FLEET_SCALE_UP_QUEUE", 8))
    # Whole-fleet idle time (no queued, no running work) before one
    # replica is retired.
    fleet_scale_down_idle_s: float = field(
        default_factory=lambda: _env_float("FLEET_SCALE_DOWN_IDLE_S",
                                           120.0))
    # Scaler control-loop cadence.
    fleet_scale_check_s: float = field(
        default_factory=lambda: _env_float("FLEET_SCALE_CHECK_S", 5.0))
    # ---- Disaggregated prefill/decode serving (router/disagg.py,
    # docs/ROUTER.md "Disaggregated prefill/decode") ----
    # Per-replica roles for the in-process fleet, comma-separated,
    # one of prefill|decode|mixed per FLEET_REPLICAS slot (e.g.
    # "prefill,decode,decode"). Empty = every replica is "mixed"
    # (today's behaviour). A prefill-role replica runs long-context
    # chunked prefill with a deep queue and ZERO decode slots — it
    # parks the finished KV and the router hands it to the decode
    # tier over the /kv/parked migration wire.
    fleet_roles: str = field(
        default_factory=lambda: _env_str("FLEET_ROLES", ""))
    # Same, for ROUTER_BACKENDS remote replicas (one role per URL).
    router_backend_roles: str = field(
        default_factory=lambda: _env_str("ROUTER_BACKEND_ROLES", ""))
    # Prompt-length threshold (estimated tokens) above which a new
    # stream takes the prefill-tier handoff path; shorter prompts
    # place decode-local. Only meaningful when the fleet has a
    # prefill-role replica.
    disagg_prefill_min_tokens: int = field(
        default_factory=lambda: _env_int("DISAGG_PREFILL_MIN_TOKENS",
                                         512))
    # ---- Session KV host-offload tier (fasttalk_tpu/kvcache/,
    # docs/KVCACHE.md) ----
    # Host-RAM budget for parked session KV (MB). 0 disables the tier
    # (evictions drop residency and a returning session re-prefills,
    # the pre-offload behaviour); negative is a config error. Values
    # above the machine's detectable RAM log a warning.
    kv_host_budget_mb: float = field(
        default_factory=lambda: _env_float("KV_HOST_BUDGET_MB", 0.0))
    # Parked entries idle past this are dropped (host RAM is a cache,
    # not an archive).
    kv_park_ttl_s: float = field(
        default_factory=lambda: _env_float("KV_PARK_TTL_S", 600.0))
    # Proactively snapshot a pinned-but-idle session after this long
    # (slot stays pinned; the copy makes a later eviction free and the
    # history restorable across engine restart). 0 disables idle parks
    # (eviction-time parks still happen).
    kv_park_idle_s: float = field(
        default_factory=lambda: _env_float("KV_PARK_IDLE_S", 30.0))
    # Matched-prefix floor below which restoring is never worth the
    # copy dispatch (the shared-prefix/delta-prefill paths serve).
    kv_restore_min_tokens: int = field(
        default_factory=lambda: _env_int("KV_RESTORE_MIN_TOKENS", 32))
    # ---- Quantized KV-cache tier (ops/kv_quant.py, docs/KVCACHE.md
    # "Quantized tier") ----
    # "none" | "int8": store the KV cache as int8 rows + per-row
    # float32 scales — ~2x resident sessions/context per HBM budget,
    # ~2x effective attention-read bandwidth, and half the bytes
    # through every park/restore/prefix copy. Explicit compatibility
    # matrix (validated below, mirrored in the engine): single-device
    # only (no tp/dp/sp mesh — the scale arrays do not shard with the
    # kv axis yet), XLA attention only (no TPU_USE_PALLAS_ATTENTION —
    # the kernel streams raw rows), and no speculative decoding (the
    # verify block's quantize-on-write is unvalidated; set
    # TPU_SPEC_DECODE=off).
    kv_quant: str = field(
        default_factory=lambda: _env_str("KV_QUANT", "none"))
    # Scale granularity: "token" (one f32 scale per (layer, slot,
    # position) row — the KIVI per-token baseline, cheapest) or
    # "head" (one per kv head per row — tighter when head magnitudes
    # diverge, at num_kv_heads x the scale storage).
    kv_quant_granule: str = field(
        default_factory=lambda: _env_str("KV_QUANT_GRANULE", "token"))
    # ---- Paged KV-cache tier (kvcache/blocks.py, docs/KVCACHE.md
    # "Paged tier") ----
    # "dense" (default) | "paged": the dense layout preallocates
    # [layers, slots, max_len, ...] — every slot priced at worst-case
    # context; the paged layout holds one flat block pool with
    # per-slot block tables, so HBM admission capacity is priced at
    # blocks actually in use and shared prefixes alias (refcount
    # bump) instead of copying rows. Single-device only (the pool and
    # tables are host-orchestrated per chip); composes with KV_QUANT,
    # the host park/offload tier, speculative + structured decoding,
    # and the Pallas decode kernel (block-walking variant).
    kv_layout: str = field(
        default_factory=lambda: _env_str("KV_LAYOUT", "dense"))
    # Tokens per block: power of two in [8, 512]. Small blocks waste
    # less tail capacity per sequence but grow the table/gather work;
    # 16 matches vLLM's default granularity.
    kv_block_size: int = field(
        default_factory=lambda: _env_int("KV_BLOCK_SIZE", 16))
    # Device pool size in blocks. 0 (default) sizes the pool to the
    # dense-equivalent HBM footprint (slots x max_len / block_size);
    # the factory lowers that to what the HBM budget actually holds,
    # which is where the paged layout admits fleets the dense layout
    # rejects.
    kv_pool_blocks: int = field(
        default_factory=lambda: _env_int("KV_POOL_BLOCKS", 0))
    # Decode-growth reserve the admission check must see free beyond
    # the prompt's blocks: "fixed" covers the next KV_RESERVE_TOKENS
    # of growth (default), "max_tokens" the request's whole token
    # budget (no mid-decode sheds, fewest admissions), "none" admits
    # on prefill fit alone (maximum packing, relies on the rehearsed
    # mid-decode shed when the pool runs dry).
    kv_reserve_policy: str = field(
        default_factory=lambda: _env_str("KV_RESERVE_POLICY", "fixed"))
    kv_reserve_tokens: int = field(
        default_factory=lambda: _env_int("KV_RESERVE_TOKENS", 128))
    # ---- Radix automatic prefix cache (kvcache/radix.py,
    # docs/KVCACHE.md "Automatic prefix cache") ----
    # Retired/parked sessions donate their clean prefix blocks to a
    # radix tree keyed by chained block hashes; every admission
    # silently aliases the longest cached chain and prefills only the
    # delta — zero explicit registration. Requires KV_LAYOUT=paged
    # (the tree holds device pool blocks; validated below with a
    # named error). Cached blocks are reclaimed LRU-first under pool
    # pressure before any live admission is shed.
    kv_radix_enabled: bool = field(
        default_factory=lambda: _env_bool("KV_RADIX_ENABLED", False))
    # Free-block headroom the cache must leave after an insert: the
    # tree evicts itself down to this floor so cached prefixes never
    # crowd out the next admission. 0 = rely on pressure eviction
    # alone. Must be < KV_POOL_BLOCKS when that is set.
    kv_radix_min_blocks: int = field(
        default_factory=lambda: _env_int("KV_RADIX_MIN_BLOCKS", 0))
    # "lru" (default): evict least-recently-matched leaves first;
    # "fifo": oldest-inserted first (cheap scans, agent workloads
    # where recency ≈ insertion order anyway).
    kv_radix_evict_policy: str = field(
        default_factory=lambda: _env_str("KV_RADIX_EVICT_POLICY",
                                         "lru"))
    # ---- Structured decoding (fasttalk_tpu/structured/,
    # docs/STRUCTURED.md) ----
    # "auto" (default): constrained requests are served whenever the
    # engine build supports them and rejected with a named reason
    # otherwise; "on": an unsupported build is a CONFIG ERROR at
    # startup (the KV-quant precedent — explicit compat matrix, no
    # silent degrade): single-device only (no tp/dp/sp mesh, no
    # multi-host SPMD) and no Pallas decode attention; "off": the
    # subsystem is disabled and every structured request 400s.
    # Speculative decoding needs no exclusion — it pauses per decode
    # call while a constrained slot is running and resumes after.
    structured_mode: str = field(
        default_factory=lambda: _env_str("STRUCTURED_MODE", "auto"))
    # Per-FSM compile bound: a schema whose token FSM exceeds this
    # many states is rejected with a 400 naming the count.
    structured_max_states: int = field(
        default_factory=lambda: _env_int("STRUCTURED_MAX_STATES", 8192))
    # Device union-arena budget: total FSM states resident across all
    # concurrently served schemas (tables bucket to powers of two
    # below this).
    structured_state_budget: int = field(
        default_factory=lambda: _env_int("STRUCTURED_STATE_BUDGET",
                                         16384))
    # Jump-forward engages when the FSM's forced single-transition
    # chain is at least this many tokens (0 disables jump-forward;
    # decode steps then emit forced tokens one model step each).
    structured_jf_min: int = field(
        default_factory=lambda: _env_int("STRUCTURED_JF_MIN", 4))
    # Compiled-FSM LRU entries per engine (keyed on the canonical
    # schema text; one entry per distinct schema/tokenizer pair).
    structured_cache: int = field(
        default_factory=lambda: _env_int("STRUCTURED_CACHE", 64))
    # response_format={"type":"json_object"} nesting depth: "any JSON"
    # is not regular, so the generic grammar unrolls to this many
    # container levels (scalars only at the innermost).
    structured_json_depth: int = field(
        default_factory=lambda: _env_int("STRUCTURED_JSON_DEPTH", 3))
    # ---- SLOs + stall watchdog (observability/slo.py, watchdog.py,
    # docs/OBSERVABILITY.md). The observability singletons read the
    # same env knobs at construction; the fields here give operators
    # one validated, discoverable surface (to_dict / docs). ----
    # Latency promises for the interactive class (ms); bulk relaxes
    # the latency targets by SLO_BULK_FACTOR (default 4x) unless
    # overridden per class (SLO_BULK_TTFT_P95_MS, ...).
    slo_ttft_p95_ms: float = field(
        default_factory=lambda: _env_float("SLO_TTFT_P95_MS", 1500.0))
    slo_inter_token_p99_ms: float = field(
        default_factory=lambda: _env_float("SLO_INTER_TOKEN_P99_MS",
                                           250.0))
    slo_queue_wait_p95_ms: float = field(
        default_factory=lambda: _env_float("SLO_QUEUE_WAIT_P95_MS",
                                           1000.0))
    slo_error_rate: float = field(
        default_factory=lambda: _env_float("SLO_ERROR_RATE", 0.01))
    # Burn-rate alert thresholds: page on fast+mid windows burning at
    # >= page_burn, warn on mid+long windows at >= warn_burn.
    slo_page_burn: float = field(
        default_factory=lambda: _env_float("SLO_PAGE_BURN", 10.0))
    slo_warn_burn: float = field(
        default_factory=lambda: _env_float("SLO_WARN_BURN", 2.0))
    # While the interactive class page-burns, shed incoming bulk at
    # admission (scheduling/scheduler.py slo_gate).
    slo_shed_bulk_on_page: bool = field(
        default_factory=lambda: _env_bool("SLO_SHED_BULK_ON_PAGE", True))
    # Watchdog: a request with no token for token_stall_s is flagged;
    # past WATCHDOG_CANCEL_STALL_S (default 2x) it is terminated with a
    # terminal error frame. An engine loop heartbeat older than
    # step_stall_s with pending work is a hung step.
    watchdog_token_stall_s: float = field(
        default_factory=lambda: _env_float("WATCHDOG_TOKEN_STALL_S",
                                           30.0))
    watchdog_step_stall_s: float = field(
        default_factory=lambda: _env_float("WATCHDOG_STEP_STALL_S",
                                           15.0))
    # Unset (-1) resolves to 2x the token stall in __post_init__,
    # matching the watchdog's own env fallback.
    watchdog_cancel_stall_s: float = field(
        default_factory=lambda: _env_float("WATCHDOG_CANCEL_STALL_S",
                                           -1.0))
    watchdog_interval_s: float = field(
        default_factory=lambda: _env_float("WATCHDOG_INTERVAL_S", 1.0))
    watchdog_loop_lag_ms: float = field(
        default_factory=lambda: _env_float("WATCHDOG_LOOP_LAG_MS",
                                           500.0))
    # Percentile-window horizon for /stats histograms (seconds): p95s
    # reflect the last metrics_window_s, not hours-old requests
    # (utils/metrics.py). <= 0 restores the pure sample-count window.
    metrics_window_s: float = field(
        default_factory=lambda: _env_float("METRICS_WINDOW_S", 300.0))
    # ---- Performance attribution ledger (observability/perf.py,
    # GET /perf + perf_* gauges) ----
    # Rolling window the attribution report covers (seconds).
    perf_window_s: float = field(
        default_factory=lambda: _env_float("PERF_WINDOW_S", 60.0))
    # Gap between device calls longer than this counts as idle (no
    # work); shorter gaps are host overhead between dispatches.
    perf_idle_gap_ms: float = field(
        default_factory=lambda: _env_float("PERF_IDLE_GAP_MS", 250.0))
    # Roofline peak for MFU (total bf16 TFLOP/s across local devices).
    # 0 = detect from the device kind; unknown kinds report mfu: null.
    perf_peak_tflops: float = field(
        default_factory=lambda: _env_float("PERF_PEAK_TFLOPS", 0.0))
    # Roofline peak for the KV-bandwidth-utilisation figure (total
    # HBM GB/s across local devices). 0 = detect from the device kind;
    # unknown kinds report kv bw_util: null.
    perf_peak_hbm_gbps: float = field(
        default_factory=lambda: _env_float("PERF_PEAK_HBM_GBPS", 0.0))
    # ---- Continuous host profiler (observability/profiler.py,
    # GET /debug/profile + host_gap_causes on /perf) ----
    # Master switch: off spawns no sampler thread and hot paths never
    # touch the profiler (pull-based), so off truly costs nothing.
    prof_enabled: bool = field(
        default_factory=lambda: _env_bool("PROF_ENABLED", True))
    # Sampling rate of the host stack sampler (Hz). 67 deliberately
    # avoids beating against 10/100 Hz periodic work.
    prof_hz: float = field(
        default_factory=lambda: _env_float("PROF_HZ", 67.0))
    # Bound on distinct collapsed stacks kept per thread role; further
    # novel stacks are counted as dropped, not stored.
    prof_max_stacks: int = field(
        default_factory=lambda: _env_int("PROF_MAX_STACKS", 2000))
    # ---- Incident flight recorder (observability/flight.py,
    # POST /debug/bundle) ----
    flight_enabled: bool = field(
        default_factory=lambda: _env_bool("FLIGHT_ENABLED", True))
    flight_dir: str = field(
        default_factory=lambda: _env_str("FLIGHT_DIR",
                                         "/tmp/fasttalk-tpu-flight"))
    # Retention: only the newest N bundle directories are kept.
    flight_max_bundles: int = field(
        default_factory=lambda: _env_int("FLIGHT_MAX_BUNDLES", 8))
    # Rate limit: at most one automatic bundle per this many seconds
    # (a page storm produces one bundle, not a disk-filling flood).
    flight_min_interval_s: float = field(
        default_factory=lambda: _env_float("FLIGHT_MIN_INTERVAL_S",
                                           120.0))
    # > 0: each bundle additionally captures a timed jax.profiler
    # device trace of the next N seconds (off the event loop).
    flight_autoprof_s: float = field(
        default_factory=lambda: _env_float("FLIGHT_AUTOPROF_S", 0.0))
    # This many serving-time recompile events within
    # flight_recompile_window_s counts as a shape-churn incident and
    # triggers a bundle.
    flight_recompile_burst: int = field(
        default_factory=lambda: _env_int("FLIGHT_RECOMPILE_BURST", 5))
    flight_recompile_window_s: float = field(
        default_factory=lambda: _env_float("FLIGHT_RECOMPILE_WINDOW_S",
                                           60.0))
    # How many newest-first events each bundle's events.json carries.
    flight_events_tail: int = field(
        default_factory=lambda: _env_int("FLIGHT_EVENTS_TAIL", 256))
    # ---- Fleet tracing + token journey (docs/OBSERVABILITY.md
    # "Fleet tracing and the token journey") ----
    # Thread the trace id across hops: traceparent headers on router →
    # replica dispatch and /kv/parked migration, adopted by the /v1
    # edge. Off = every process minds its own traces (stitching still
    # works per-process, cross-replica timelines don't).
    trace_propagate: bool = field(
        default_factory=lambda: _env_bool("TRACE_PROPAGATE", True))
    # Server-side kill switch for per-token journey attribution; the
    # per-session journey:true opt-in is ignored when false.
    journey_enabled: bool = field(
        default_factory=lambda: _env_bool("JOURNEY_ENABLED", True))
    # Reconciliation tolerance for derived checks (trace_report.py
    # --journey): |1 - hop_sum/wall| must stay within this fraction.
    journey_tol: float = field(
        default_factory=lambda: _env_float("JOURNEY_TOL", 0.10))
    # ---- Fleet flight recorder (observability/fleetflight.py):
    # router-side incident triggers fan bundle collection out to every
    # live replica (router-fronted processes only) ----
    fleet_flight_enabled: bool = field(
        default_factory=lambda: _env_bool("FLEET_FLIGHT_ENABLED", True))
    fleet_flight_dir: str = field(
        default_factory=lambda: _env_str(
            "FLEET_FLIGHT_DIR", "/tmp/fasttalk-tpu-fleet-flight"))
    fleet_flight_max_bundles: int = field(
        default_factory=lambda: _env_int("FLEET_FLIGHT_MAX_BUNDLES", 4))
    fleet_flight_min_interval_s: float = field(
        default_factory=lambda: _env_float("FLEET_FLIGHT_MIN_INTERVAL_S",
                                           120.0))
    # This many failovers within fleet_flight_window_s counts as a
    # failover burst and triggers a fleet bundle.
    fleet_flight_failover_burst: int = field(
        default_factory=lambda: _env_int("FLEET_FLIGHT_FAILOVER_BURST",
                                         3))
    fleet_flight_window_s: float = field(
        default_factory=lambda: _env_float("FLEET_FLIGHT_WINDOW_S",
                                           60.0))
    # Pre-compile hot shapes at startup: "off" | "fast" | "full" — the
    # in-tree replacement for the reference's 300s engine-container
    # health start_period (docker-compose.vllm.yml:62-67). Empty means
    # provider-dependent: "fast" for the in-tree tpu engine (so the bare
    # `python main.py websocket` never serves first traffic through
    # 20-40s XLA compiles), "off" for remote/fake providers which have
    # nothing to compile.
    warmup: str = field(default_factory=lambda: _env_str("TPU_WARMUP", ""))

    def __post_init__(self) -> None:
        if not self.warmup:
            self.warmup = "fast" if self.llm_provider == "tpu" else "off"
        if self.watchdog_cancel_stall_s == -1.0:  # unset: 2x token stall
            self.watchdog_cancel_stall_s = 2.0 * self.watchdog_token_stall_s
        if self.default_repeat_penalty < 0:  # unset: provider-resolved
            self.default_repeat_penalty = \
                1.0 if self.llm_provider == "vllm" else 1.1
        # WEIGHT_QUANT unset: resolve it from the legacy TPU_QUANTIZE
        # knob; set: it is authoritative, and the legacy field is
        # brought into agreement (everything downstream may read
        # either). Both set to DIFFERENT tiers is a named error in
        # _validate, not a silent precedence.
        if not self.weight_quant:
            self.weight_quant = {"none": "off"}.get(self.quantize,
                                                    self.quantize)
        elif self.quantize == "none" \
                and self.weight_quant in ("off", "int8", "int4"):
            self.quantize = {"off": "none"}.get(self.weight_quant,
                                                self.weight_quant)
        self._validate()

    def _validate(self) -> None:
        errs: list[str] = []
        if self.compute_device not in VALID_DEVICES:
            errs.append(f"compute_device must be one of {VALID_DEVICES}")
        if self.llm_provider not in VALID_PROVIDERS:
            errs.append(f"llm_provider must be one of {VALID_PROVIDERS}")
        if not (0.0 <= self.default_temperature <= 2.0):
            errs.append("default_temperature must be in [0, 2]")
        if not (0.0 < self.default_top_p <= 1.0):
            errs.append("default_top_p must be in (0, 1]")
        if self.default_top_k < 0:
            errs.append("default_top_k must be >= 0")
        if self.default_max_tokens <= 0:
            errs.append("default_max_tokens must be > 0")
        if not (0.0 < self.default_repeat_penalty <= 2.0):
            errs.append("default_repeat_penalty must be in (0, 2]")
        if not (-2.0 <= self.default_presence_penalty <= 2.0):
            errs.append("default_presence_penalty must be in [-2, 2]")
        if not (-2.0 <= self.default_frequency_penalty <= 2.0):
            errs.append("default_frequency_penalty must be in [-2, 2]")
        if self.port == self.monitoring_port:
            errs.append("port and monitoring_port must differ")
        if self.max_connections <= 0:
            errs.append("max_connections must be > 0")
        if self.decode_slots <= 0:
            errs.append("decode_slots must be > 0")
        if self.max_model_len <= 0:
            errs.append("max_model_len must be > 0")
        if self.prefill_chunk <= 0 or self.prefill_chunk & (self.prefill_chunk - 1):
            errs.append("prefill_chunk must be a positive power of two")
        if self.tp_size <= 0 or self.dp_size <= 0 or self.sp_size <= 0:
            errs.append("tp_size, dp_size and sp_size must be >= 1")
        if self.spmd_role not in ("off", "leader", "follower"):
            errs.append("spmd_role must be off|leader|follower")
        if self.spmd_role != "off":
            if ":" not in self.spmd_addr:
                errs.append("spmd_addr must be host:port")
            if self.spmd_followers <= 0:
                errs.append("spmd_followers must be >= 1")
        if self.spmd_hb_interval_s < 0:
            errs.append("spmd_hb_interval_s must be >= 0 (0 disables "
                        "the leader heartbeat beacon)")
        if self.spmd_hb_timeout_s < 0:
            errs.append("spmd_hb_timeout_s must be >= 0 (0 disables "
                        "the follower recv deadline)")
        if self.spmd_hb_interval_s > 0 and self.spmd_hb_timeout_s > 0 \
                and self.spmd_hb_timeout_s <= self.spmd_hb_interval_s:
            errs.append(
                "spmd_hb_timeout_s must exceed spmd_hb_interval_s "
                "(a deadline shorter than the beacon period declares "
                "a healthy leader dead)")
        if self.spmd_hb_interval_s == 0 and self.spmd_hb_timeout_s > 0:
            errs.append(
                "SPMD_HB_INTERVAL_S=0 (heartbeats off) requires "
                "SPMD_HB_TIMEOUT_S=0: a follower recv deadline with "
                "no heartbeats on the wire declares a healthy idle "
                "leader dead")
        if self.supervisor_max_restarts < 1:
            errs.append("supervisor_max_restarts must be >= 1")
        if self.supervisor_window_s <= 0:
            errs.append("supervisor_window_s must be > 0")
        if self.supervisor_backoff_s <= 0:
            errs.append("supervisor_backoff_s must be > 0")
        if self.fault_points.strip():
            # Validate the fault-injection spec at startup so a chaos
            # drill with a typo'd point/action is a NAMED config
            # error, never a silently disabled drill
            # (resilience/failpoints.py parse_spec).
            try:
                from fasttalk_tpu.resilience.failpoints import \
                    parse_spec

                parse_spec(self.fault_points)
            except ValueError as e:
                errs.append(str(e))
        if self.decode_steps_per_call <= 0:
            errs.append("decode_steps_per_call must be >= 1")
        if self.spec_decode not in ("off", "ngram", "auto"):
            errs.append(
                f"spec_decode must be off|ngram|auto, "
                f"got {self.spec_decode!r}")
        if self.spec_decode != "off" and not 1 <= self.spec_draft_len <= 31:
            errs.append("spec_draft_len must be in 1..31")
        if self.spec_breakeven <= 0:
            errs.append("spec_breakeven must be > 0")
        if self.pipeline_depth <= 0:
            errs.append("pipeline_depth must be >= 1")
        if self.sampling not in ("fast", "exact"):
            errs.append(f"TPU_SAMPLING must be fast|exact, "
                        f"got {self.sampling!r}")
        if self.quantize not in ("none", "int8", "int4"):
            errs.append("quantize must be 'none', 'int8' or 'int4'")
        # Int4 weight-tier knobs (docs/QUANTIZATION.md): explicit
        # compatibility matrix, mirroring KV_QUANT=int8 below — every
        # unsupported combination is a NAMED startup error, never a
        # silent fall-back.
        if self.weight_quant not in ("off", "int8", "int4"):
            errs.append(f"WEIGHT_QUANT must be off|int8|int4, "
                        f"got {self.weight_quant!r}")
        elif self.quantize in ("none", "int8", "int4") \
                and {"off": "none"}.get(self.weight_quant,
                                        self.weight_quant) != self.quantize:
            errs.append(
                f"WEIGHT_QUANT={self.weight_quant} conflicts with "
                f"legacy TPU_QUANTIZE={self.quantize}; set only "
                f"WEIGHT_QUANT (TPU_QUANTIZE is its alias)")
        if self.weight_quant_group < 2 or self.weight_quant_group % 2:
            errs.append(
                f"WEIGHT_QUANT_GROUP must be an even integer >= 2 (int4 "
                f"packs adjacent rows into one byte, so a scale group "
                f"must never split a nibble pair), got "
                f"{self.weight_quant_group}")
        if self.weight_quant_calib and self.weight_quant_calib != "corpus" \
                and not os.path.isfile(self.weight_quant_calib):
            errs.append(
                f"WEIGHT_QUANT_CALIB must be '' (data-free), 'corpus', "
                f"or a readable prompt file (one per line); no file at "
                f"{self.weight_quant_calib!r}")
        if self.use_pallas_int4 and self.weight_quant != "int4":
            errs.append(
                "TPU_USE_PALLAS_INT4=true requires WEIGHT_QUANT=int4 "
                "(the kernel reads nibble-packed {'q4','s'} leaves)")
        if self.weight_quant == "int4":
            if self.tp_size > 1 or self.dp_size > 1 or self.sp_size > 1:
                errs.append(
                    "WEIGHT_QUANT=int4 is single-device only in v1 "
                    "(partition rules for the q4/scale leaves exist — "
                    "parallel/sharding.py — but the sharded load/init "
                    "path is unvalidated); set TPU_TP_SIZE=TPU_DP_SIZE="
                    "TPU_SP_SIZE=1")
            if self.spmd_role != "off":
                errs.append("WEIGHT_QUANT=int4 is incompatible with "
                            "multi-host SPMD serving; set "
                            "TPU_SPMD_ROLE=off")
        if self.sched_queue_bound <= 0:
            errs.append("sched_queue_bound must be > 0")
        if self.sched_default_deadline_s <= 0:
            errs.append("sched_default_deadline_s must be > 0")
        if self.sched_default_priority not in ("interactive", "bulk"):
            errs.append("sched_default_priority must be "
                        "'interactive' or 'bulk'")
        if self.sched_bulk_aging_s <= 0:
            errs.append("sched_bulk_aging_s must be > 0")
        if self.sched_drain_timeout_s < 0:
            errs.append("sched_drain_timeout_s must be >= 0")
        if self.remote_max_inflight <= 0:
            errs.append("remote_max_inflight must be > 0")
        if self.remote_connect_retries < 0:
            errs.append("remote_connect_retries must be >= 0 "
                        "(0 disables the pre-first-token retry)")
        if self.fleet_replicas < 0:
            errs.append("fleet_replicas must be >= 0")
        if self.router_probe_interval_s < 0:
            errs.append("router_probe_interval_s must be >= 0 "
                        "(0 disables the probe thread)")
        if self.router_affinity_ttl_s <= 0:
            errs.append("router_affinity_ttl_s must be > 0")
        if self.router_failover_retries < 0:
            errs.append("router_failover_retries must be >= 0")
        if self.router_dead_probes < 1:
            errs.append("router_dead_probes must be >= 1")
        if self.router_migrate_timeout_s <= 0:
            errs.append("router_migrate_timeout_s must be > 0 (a hung "
                        "migration must never wedge a drain; disable "
                        "migration with ROUTER_MIGRATE=false instead)")
        if self.fleet_scale_min < 1:
            errs.append("fleet_scale_min must be >= 1 (the fleet "
                        "never scales to zero replicas)")
        if self.fleet_scale_max < 0:
            errs.append("fleet_scale_max must be >= 0 (0 disables "
                        "elastic scaling)")
        if self.fleet_scale_max > 0 \
                and self.fleet_scale_max < self.fleet_scale_min:
            errs.append(f"fleet_scale_max ({self.fleet_scale_max}) "
                        f"must be >= fleet_scale_min "
                        f"({self.fleet_scale_min})")
        if self.fleet_scale_max > 0 and not self.router_enabled:
            errs.append("FLEET_SCALE_MAX > 0 requires "
                        "ROUTER_ENABLED=true (the elastic scaler "
                        "drives a FleetRouter)")
        if self.fleet_scale_up_queue < 1:
            errs.append("fleet_scale_up_queue must be >= 1")
        if self.fleet_scale_down_idle_s <= 0:
            errs.append("fleet_scale_down_idle_s must be > 0")
        if self.fleet_scale_check_s <= 0:
            errs.append("fleet_scale_check_s must be > 0")
        _role_values = ("prefill", "decode", "mixed")
        _all_roles: list[str] = []
        for spec, env, count, what in (
                (self.fleet_roles, "FLEET_ROLES",
                 self.fleet_replicas, "FLEET_REPLICAS"),
                (self.router_backend_roles, "ROUTER_BACKEND_ROLES",
                 len([u for u in self.router_backends.split(",")
                      if u.strip()]), "ROUTER_BACKENDS"),
        ):
            if not spec.strip():
                continue
            roles = [r.strip().lower() for r in spec.split(",")]
            bad = [r for r in roles if r not in _role_values]
            if bad:
                errs.append(f"{env} contains invalid role(s) "
                            f"{bad!r} (each must be one of "
                            f"prefill|decode|mixed)")
                continue
            if len(roles) != count:
                errs.append(f"{env} lists {len(roles)} role(s) but "
                            f"{what} defines {count} replica(s) — "
                            "one role per replica, in order")
                continue
            _all_roles.extend(roles)
        if _all_roles:
            if not self.router_enabled:
                errs.append("FLEET_ROLES/ROUTER_BACKEND_ROLES require "
                            "ROUTER_ENABLED=true (replica roles are a "
                            "router placement concept)")
            if "prefill" in _all_roles and not self.router_migrate:
                errs.append("a 'prefill' replica role requires "
                            "ROUTER_MIGRATE=true (prefill replicas "
                            "hand finished KV to the decode tier over "
                            "the /kv/parked migration wire; without "
                            "migration their output is unreachable)")
            if "prefill" in _all_roles \
                    and not any(r in ("decode", "mixed")
                                for r in _all_roles):
                errs.append("a fleet with 'prefill' roles needs at "
                            "least one 'decode' or 'mixed' replica to "
                            "run the decode side of the handoff")
        if self.disagg_prefill_min_tokens < 1:
            errs.append("disagg_prefill_min_tokens must be >= 1")
        if self.router_enabled:
            n_remote = len([u for u in self.router_backends.split(",")
                            if u.strip()])
            if self.fleet_replicas + n_remote < 1:
                errs.append("router_enabled needs at least one replica "
                            "(FLEET_REPLICAS >= 1 or ROUTER_BACKENDS)")
            if self.spmd_role != "off":
                errs.append("router_enabled is incompatible with "
                            "multi-host SPMD serving (spmd_role must "
                            "be 'off'; an SPMD cluster is ONE logical "
                            "replica — front it via ROUTER_BACKENDS "
                            "from a separate router process)")
        if self.kv_host_budget_mb < 0:
            errs.append("kv_host_budget_mb must be >= 0 (0 disables "
                        "the host-offload tier)")
        if self.kv_park_ttl_s <= 0:
            errs.append("kv_park_ttl_s must be > 0")
        if self.kv_park_idle_s < 0:
            errs.append("kv_park_idle_s must be >= 0 (0 disables "
                        "idle parking)")
        if self.kv_restore_min_tokens < 1:
            errs.append("kv_restore_min_tokens must be >= 1")
        if self.kv_quant not in ("none", "int8"):
            errs.append("kv_quant must be 'none' or 'int8'")
        if self.kv_quant_granule not in ("token", "head"):
            errs.append("kv_quant_granule must be 'token' or 'head'")
        if self.kv_quant == "int8":
            # The quantized tier's compatibility matrix is explicit:
            # every unsupported combination fails HERE with the reason,
            # never silently degrades to bf16 (docs/KVCACHE.md).
            if self.tp_size > 1 or self.dp_size > 1 or self.sp_size > 1:
                errs.append(
                    "KV_QUANT=int8 is single-device only (the per-row "
                    "scale arrays do not shard with the kv axis yet); "
                    "set TPU_TP_SIZE=TPU_DP_SIZE=TPU_SP_SIZE=1")
            if self.spmd_role != "off":
                errs.append("KV_QUANT=int8 is incompatible with "
                            "multi-host SPMD serving (sharded cache); "
                            "set TPU_SPMD_ROLE=off")
            # The Pallas decode-attention kernel composes with this
            # tier: int8 rows + scale arrays DMA into VMEM and
            # dequantize inside the kernel (ops/pallas_attention.py) —
            # no guard needed.
            if self.spec_decode != "off":
                errs.append(
                    "KV_QUANT=int8 is incompatible with speculative "
                    "decoding (the spec carry does not thread the "
                    "scale arrays through the verify block) — set "
                    "TPU_SPEC_DECODE=off")
        if self.kv_layout not in ("dense", "paged"):
            errs.append(f"kv_layout must be 'dense' or 'paged', "
                        f"got {self.kv_layout!r}")
        if (self.kv_block_size < 8 or self.kv_block_size > 512
                or self.kv_block_size & (self.kv_block_size - 1)):
            errs.append(f"kv_block_size must be a power of two in "
                        f"[8, 512], got {self.kv_block_size}")
        if self.kv_pool_blocks < 0:
            errs.append("kv_pool_blocks must be >= 0 (0 sizes the pool "
                        "to the dense-equivalent footprint)")
        if self.kv_reserve_policy not in ("none", "fixed", "max_tokens"):
            errs.append(f"kv_reserve_policy must be none|fixed|"
                        f"max_tokens, got {self.kv_reserve_policy!r}")
        if self.kv_reserve_tokens < 0:
            errs.append("kv_reserve_tokens must be >= 0")
        if self.kv_layout == "paged":
            # Paged compat matrix (docs/KVCACHE.md): named startup
            # errors, never a silent fall-back to dense.
            if self.tp_size > 1 or self.dp_size > 1 or self.sp_size > 1:
                errs.append(
                    "KV_LAYOUT=paged is single-device only (the block "
                    "pool and per-slot tables are host-orchestrated "
                    "per chip); set TPU_TP_SIZE=TPU_DP_SIZE="
                    "TPU_SP_SIZE=1")
            if self.spmd_role != "off":
                errs.append("KV_LAYOUT=paged is incompatible with "
                            "multi-host SPMD serving; set "
                            "TPU_SPMD_ROLE=off")
            if self.kv_block_size > self.max_model_len:
                errs.append(
                    f"kv_block_size ({self.kv_block_size}) must not "
                    f"exceed max_model_len ({self.max_model_len})")
        # Radix prefix-cache compat matrix (docs/KVCACHE.md "Automatic
        # prefix cache"): named startup errors, mirrored in the engine.
        if self.kv_radix_enabled and self.kv_layout != "paged":
            errs.append(
                "KV_RADIX_ENABLED=true requires KV_LAYOUT=paged (the "
                "radix prefix cache holds device pool blocks; the "
                "dense layout has no block pool to cache into)")
        if self.kv_radix_min_blocks < 0:
            errs.append("kv_radix_min_blocks must be >= 0")
        elif self.kv_radix_enabled and self.kv_pool_blocks \
                and self.kv_radix_min_blocks >= self.kv_pool_blocks:
            errs.append(
                f"kv_radix_min_blocks ({self.kv_radix_min_blocks}) "
                f"must be < kv_pool_blocks ({self.kv_pool_blocks}) — "
                "a headroom floor covering the whole pool leaves the "
                "cache nothing to hold")
        if self.kv_radix_evict_policy not in ("lru", "fifo"):
            errs.append(f"kv_radix_evict_policy must be lru|fifo, "
                        f"got {self.kv_radix_evict_policy!r}")
        if self.structured_mode not in ("auto", "on", "off"):
            errs.append(f"structured_mode must be auto|on|off, "
                        f"got {self.structured_mode!r}")
        if self.structured_max_states < 16:
            errs.append(f"structured_max_states must be >= 16, "
                        f"got {self.structured_max_states}")
        if self.structured_state_budget < self.structured_max_states:
            errs.append(
                f"structured_state_budget "
                f"({self.structured_state_budget}) must be >= "
                f"structured_max_states ({self.structured_max_states}) "
                "or the largest admissible FSM could never be pinned")
        if self.structured_jf_min < 0:
            errs.append(f"structured_jf_min must be >= 0 (0 disables "
                        f"jump-forward), got {self.structured_jf_min}")
        if self.structured_cache < 1:
            errs.append(f"structured_cache must be >= 1, "
                        f"got {self.structured_cache}")
        if not 1 <= self.structured_json_depth <= 8:
            errs.append(f"structured_json_depth must be in 1..8, "
                        f"got {self.structured_json_depth}")
        if self.structured_mode == "on":
            # Explicit opt-in makes the compat matrix a startup error
            # with the reason, mirroring KV_QUANT=int8 (docs/
            # STRUCTURED.md): never silently degrade.
            if self.tp_size > 1 or self.dp_size > 1 or self.sp_size > 1:
                errs.append(
                    "STRUCTURED_MODE=on is single-device only in v1 "
                    "(per-slot FSM state is not threaded through the "
                    "sharded decode path); set "
                    "TPU_TP_SIZE=TPU_DP_SIZE=TPU_SP_SIZE=1 or "
                    "STRUCTURED_MODE=auto")
            if self.spmd_role != "off":
                errs.append("STRUCTURED_MODE=on is incompatible with "
                            "multi-host SPMD serving; set "
                            "TPU_SPMD_ROLE=off")
            # The Pallas decode-attention kernel now rides the scatter
            # decode path (pallas_dense/pallas_paged in forward_decode),
            # so constrained decoding composes with it — no guard.
        if self.kv_host_budget_mb > 0:
            # Warn (don't fail) when the budget exceeds detectable host
            # RAM: the pool would page/OOM long before filling.
            try:
                import psutil

                total_mb = psutil.virtual_memory().total / (1024 * 1024)
                if self.kv_host_budget_mb > total_mb:
                    import logging

                    logging.getLogger("fasttalk.config").warning(
                        "KV_HOST_BUDGET_MB=%.0f exceeds detectable "
                        "host RAM (%.0f MB); the pool will hit swap "
                        "or the OOM killer before its budget",
                        self.kv_host_budget_mb, total_mb)
            except Exception:
                pass
        for name in ("slo_ttft_p95_ms", "slo_inter_token_p99_ms",
                     "slo_queue_wait_p95_ms", "slo_page_burn",
                     "slo_warn_burn", "watchdog_token_stall_s",
                     "watchdog_step_stall_s", "watchdog_interval_s",
                     "watchdog_cancel_stall_s", "watchdog_loop_lag_ms"):
            if getattr(self, name) <= 0:
                errs.append(f"{name} must be > 0")
        if not (0.0 < self.slo_error_rate <= 1.0):
            errs.append("slo_error_rate must be in (0, 1]")
        if self.perf_window_s <= 0:
            errs.append("perf_window_s must be > 0")
        if self.perf_idle_gap_ms <= 0:
            errs.append("perf_idle_gap_ms must be > 0")
        if self.perf_peak_tflops < 0:
            errs.append("perf_peak_tflops must be >= 0 (0 = detect "
                        "from the device kind)")
        if self.perf_peak_hbm_gbps < 0:
            errs.append("perf_peak_hbm_gbps must be >= 0 (0 = detect "
                        "from the device kind)")
        if self.prof_hz <= 0 or self.prof_hz > 1000:
            errs.append("prof_hz must be in (0, 1000] — the host "
                        "stack sampler rate in Hz")
        if self.prof_max_stacks < 16:
            errs.append("prof_max_stacks must be >= 16 (the bound on "
                        "distinct stacks kept per thread role)")
        if not self.flight_dir.strip():
            errs.append("flight_dir must be a non-empty path")
        if self.flight_max_bundles < 1:
            errs.append("flight_max_bundles must be >= 1")
        if self.flight_min_interval_s < 0:
            errs.append("flight_min_interval_s must be >= 0")
        if self.flight_autoprof_s < 0:
            errs.append("flight_autoprof_s must be >= 0 (0 disables "
                        "the automatic profiler capture)")
        if self.flight_recompile_burst < 2:
            errs.append("flight_recompile_burst must be >= 2 (one "
                        "recompile is an event, not an incident)")
        if self.flight_recompile_window_s <= 0:
            errs.append("flight_recompile_window_s must be > 0")
        if self.flight_events_tail < 1:
            errs.append("flight_events_tail must be >= 1")
        if not (0 < self.journey_tol < 1):
            errs.append("journey_tol must be in (0, 1) — a fraction "
                        "of wall clock the hop sum may miss by")
        if not self.fleet_flight_dir.strip():
            errs.append("fleet_flight_dir must be a non-empty path")
        if self.fleet_flight_max_bundles < 1:
            errs.append("fleet_flight_max_bundles must be >= 1")
        if self.fleet_flight_min_interval_s < 0:
            errs.append("fleet_flight_min_interval_s must be >= 0")
        if self.fleet_flight_failover_burst < 2:
            errs.append("fleet_flight_failover_burst must be >= 2 "
                        "(one failover is an event, not an incident)")
        if self.fleet_flight_window_s <= 0:
            errs.append("fleet_flight_window_s must be > 0")
        if self.watchdog_cancel_stall_s < self.watchdog_token_stall_s:
            # Cancellation cannot precede detection; a smaller value
            # would silently mean max(token, cancel) (watchdog.py).
            errs.append("watchdog_cancel_stall_s must be >= "
                        "watchdog_token_stall_s")
        if self.warmup not in ("off", "fast", "full"):
            errs.append("warmup must be 'off', 'fast' or 'full'")
        if self.default_context_window < self.default_max_tokens:
            # Reference warns here (config.py:184-187); we keep it a warning.
            pass
        if errs:
            raise ValueError("Invalid configuration: " + "; ".join(errs))

    # Presets mirror reference config.py:270-315 (fast/balanced/quality).
    def apply_preset(self, name: str) -> None:
        presets = {
            "fast": dict(default_temperature=0.5, default_max_tokens=512,
                         default_top_p=0.85, default_top_k=20),
            "balanced": dict(default_temperature=0.7, default_max_tokens=2048,
                             default_top_p=0.9, default_top_k=40),
            "quality": dict(default_temperature=0.9, default_max_tokens=4096,
                            default_top_p=0.95, default_top_k=80),
        }
        if name not in presets:
            raise ValueError(f"Unknown preset {name!r}; choose from {sorted(presets)}")
        for k, v in presets[name].items():
            setattr(self, k, v)
        self._validate()

    def to_dict(self) -> dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


_config: Config | None = None


def get_config(reload: bool = False) -> Config:
    global _config
    if _config is None or reload:
        _config = Config()
    return _config
