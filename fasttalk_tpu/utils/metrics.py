"""Single process-wide metrics registry with Prometheus text output.

Deliberately replaces the reference's three overlapping mechanisms
(SURVEY.md §5: connection_manager counters + conversation_manager counters +
the never-wired ServiceMonitor at app/monitoring/service_monitor.py:18-61,
whose /metrics always reported zeros). One registry, one source of truth,
real tokenizer token counts.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
import time
from collections import deque
from typing import Any, Iterable


def _default_window_s() -> float:
    """Percentile-window horizon (seconds); <= 0 disables time-based
    eviction (pure sample-count window, the pre-ISSUE-3 behaviour)."""
    raw = os.getenv("METRICS_WINDOW_S", "").strip()
    if not raw:
        return 300.0
    try:
        return float(raw)
    except ValueError:
        return 300.0


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def clear(self) -> None:
        with self._lock:
            self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def clear(self) -> None:
        with self._lock:
            self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class LabeledGauge:
    """A gauge family keyed by ONE label (e.g. per-program busy
    seconds). The flat Gauge stays the default — labels multiply
    cardinality and most of this registry is deliberately scalar —
    but per-program / per-cause attribution is exactly the case
    labels exist for, and flattening the label into the metric name
    would break every PromQL aggregation over the family.

    ``set_all`` replaces the whole family atomically: attribution
    samples are recomputed per scrape, and stale members (a program
    that left the rolling window) must disappear rather than freeze
    at their last value."""

    def __init__(self, name: str, help_: str, label: str):
        self.name = name
        self.help = help_
        self.label = label
        self._values: dict[str, float] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: float) -> None:
        with self._lock:
            self._values[key] = value

    def set_all(self, values: dict[str, float]) -> None:
        with self._lock:
            self._values = dict(values)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    @property
    def value(self) -> dict[str, float]:
        with self._lock:
            return dict(self._values)

    @staticmethod
    def _escape_label(value: str) -> str:
        """Label-value escaping per the exposition format: backslash,
        double quote and newline."""
        return (value.replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))


class Histogram:
    """Fixed-bucket histogram; also keeps a bounded sample window so the
    /stats endpoint can report true percentiles (p50/p95 TTFT etc.).

    The percentile window is bounded BOTH ways: at most ``window``
    samples AND nothing older than ``window_s`` seconds
    (``METRICS_WINDOW_S``, default 300). The count bound alone meant
    that under low traffic /stats p95s reflected hours-old requests —
    an incident stayed "visible" in the percentiles long after it
    ended, and a quiet regression hid behind yesterday's good samples.
    The cumulative bucket counts are untouched: Prometheus rate() math
    needs monotonic counters, and gets them.
    """

    def __init__(self, name: str, help_: str, buckets: Iterable[float],
                 window: int = 2048, window_s: float | None = None,
                 clock=time.monotonic):
        self.name = name
        self.help = help_
        self.buckets = sorted(buckets)
        self.window_s = _default_window_s() if window_s is None \
            else window_s
        self._clock = clock
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._window: deque[tuple[float, float]] = deque(maxlen=window)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            i = bisect.bisect_left(self.buckets, value)
            self._counts[i] += 1
            self._sum += value
            self._n += 1
            now = self._clock()
            self._window.append((now, value))
            self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        """Drop window samples older than window_s (amortised O(1):
        entries leave at most once). Bucket counts are cumulative and
        never pruned."""
        if self.window_s <= 0:
            return
        horizon = now - self.window_s
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()

    def _window_values_locked(self) -> list[float]:
        self._prune_locked(self._clock())
        return [v for _, v in self._window]

    def clear(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._n = 0
            self._window.clear()

    @staticmethod
    def _quantile(sorted_window: list[float], q: float) -> float:
        """Nearest-rank percentile: the smallest value with at least
        q% of the window at or below it (the truncating-index form
        biased small windows high — p50 of [1..4] picked 3)."""
        if not sorted_window:
            return 0.0
        idx = min(len(sorted_window) - 1,
                  max(0, math.ceil(q / 100.0 * len(sorted_window)) - 1))
        return sorted_window[idx]

    def percentile(self, q: float) -> float:
        with self._lock:
            s = sorted(self._window_values_locked())
        return self._quantile(s, q)

    def summary(self) -> dict[str, float]:
        with self._lock:  # one consistent snapshot, one sort
            n, total = self._n, self._sum
            s = sorted(self._window_values_locked())
        return {
            "count": n,
            "sum": total,
            "mean": total / n if n else 0.0,
            "p50": self._quantile(s, 50),
            "p95": self._quantile(s, 95),
            "p99": self._quantile(s, 99),
        }


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Gauge | Histogram
                            | LabeledGauge] = {}
        self._lock = threading.Lock()
        self.started_at = time.time()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_), Gauge)

    def labeled_gauge(self, name: str, help_: str = "",
                      label: str = "key") -> LabeledGauge:
        return self._get_or_create(
            name, lambda: LabeledGauge(name, help_, label), LabeledGauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Iterable[float] = (
                      1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
                  ) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_, buckets), Histogram)

    def _get_or_create(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def uptime(self) -> float:
        return time.time() - self.started_at

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"uptime_seconds": self.uptime()}
        with self._lock:
            metrics = dict(self._metrics)
        for name, m in metrics.items():
            if isinstance(m, Histogram):
                out[name] = m.summary()
            else:
                out[name] = m.value  # LabeledGauge: {label_value: v}
        return out

    @staticmethod
    def _escape_help(text: str) -> str:
        """HELP-line escaping per the exposition format: backslash and
        newline only (a literal newline would truncate the line and the
        scraper would reject the next one)."""
        return text.replace("\\", "\\\\").replace("\n", "\\n")

    @staticmethod
    def _fmt_le(bound: float) -> str:
        """Bucket bounds render as canonical floats ("1.0", "2.5"),
        matching prometheus_client — int-vs-float formatting made the
        same bound render two ways across histograms."""
        return repr(float(bound))

    def prometheus(self) -> str:
        """Render all metrics in Prometheus exposition text format."""
        lines: list[str] = []
        with self._lock:
            metrics = dict(self._metrics)
        for name, m in metrics.items():
            if m.help:
                lines.append(f"# HELP {name} {self._escape_help(m.help)}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {m.value}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {m.value}")
            elif isinstance(m, LabeledGauge):
                lines.append(f"# TYPE {name} gauge")
                vals = m.value
                for key in sorted(vals):
                    esc = m._escape_label(key)
                    lines.append(
                        f'{name}{{{m.label}="{esc}"}} {vals[key]}')
            else:
                lines.append(f"# TYPE {name} histogram")
                acc = 0
                with m._lock:
                    counts, total, n = list(m._counts), m._sum, m._n
                for bound, c in zip(m.buckets, counts):
                    acc += c
                    lines.append(
                        f'{name}_bucket{{le="{self._fmt_le(bound)}"}} {acc}')
                lines.append(f'{name}_bucket{{le="+Inf"}} {n}')
                lines.append(f"{name}_sum {total}")
                lines.append(f"{name}_count {n}")
        lines.append("")
        return "\n".join(lines)


_registry: MetricsRegistry | None = None


def get_metrics() -> MetricsRegistry:
    global _registry
    if _registry is None:
        _registry = MetricsRegistry()
    return _registry


def reset_metrics() -> None:
    """Test hook: zero every metric IN PLACE (and restart the uptime
    clock), keeping registry and metric object identity.

    Dropping the registry — the old behaviour — orphaned every metric
    object cached at module/instance construction time (engine._m_*,
    ConnectionManager counters, ...): they kept incrementing objects no
    registry would ever render, so tests (and any runtime caller of
    reset) silently lost all subsequent counts."""
    global _registry
    if _registry is None:
        return
    with _registry._lock:
        metrics = list(_registry._metrics.values())
    for m in metrics:
        m.clear()
    _registry.started_at = time.time()
