"""The native tool-calling agent: detect → execute → resume, in-stream.

Replaces the reference's PydanticAI agent (app/agents/voice_agent.py:
85-344, whose tool loop lived inside the pydantic_ai library and whose
parsing lived inside vLLM's --tool-call-parser flag) with a loop this
framework owns end to end, running directly on the in-process engine:

  1. prepend a hermes-format tool section to the system prompt,
  2. stream from the engine while the HermesStreamParser scans deltas,
  3. on a completed <tool_call>: suppress its markup, emit a tool_call
     event (so clients can render activity), execute via the registry,
     append the call + <tool_response> to the message list, and resume
     generation with the grown history — the engine's prefix-reuse makes
     the resume prefill only the delta,
  4. bounded by max_tool_rounds to prevent loops.

Exposes the same event-stream seam as EngineBase, so the serving layer
treats agent and bare engine identically.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, AsyncGenerator

from fasttalk_tpu.agents.hermes import (
    HermesStreamParser,
    format_tool_result,
    inject_tools_section,
    tools_system_prompt,
)
from fasttalk_tpu.agents.tools import ToolRegistry, build_default_registry
from fasttalk_tpu.engine.engine import EngineBase, GenerationParams
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("agents.voice_agent")


class VoiceAgent:
    def __init__(self, engine: EngineBase, config: Any = None,
                 registry: ToolRegistry | None = None,
                 max_tool_rounds: int = 4):
        self.engine = engine
        self.max_tool_rounds = max_tool_rounds
        if registry is not None:
            self.registry = registry
        else:
            from fasttalk_tpu.agents.search import backend_from_config

            enable_search = bool(getattr(config, "enable_web_search", True))
            rate = float(getattr(config, "web_search_rate_limit", 1.0))
            self.registry = build_default_registry(
                enable_web_search=enable_search,
                search_backend=(backend_from_config(config)
                                if enable_search else None),
                search_rate_limit_s=rate)
        self._m_calls = get_metrics().counter(
            "agent_tool_calls_total", "tool calls executed by the agent")
        # top-level request id -> currently running engine sub-request id,
        # so cancel(top_id) reaches the live engine request.
        self._active_sub: dict[str, str] = {}

    def update_config(self, **overrides: Any) -> None:
        if "max_tool_rounds" in overrides:
            self.max_tool_rounds = int(overrides["max_tool_rounds"])

    def _augment_system(self, messages: list[dict]) -> list[dict]:
        specs = self.registry.specs()
        if not specs:
            return messages
        return inject_tools_section(messages, tools_system_prompt(specs))

    async def generate(self, request_id: str, session_id: str,
                       messages: list[dict], params: GenerationParams,
                       ) -> AsyncGenerator[dict, None]:
        """Event stream: token / tool_call / done|cancelled|error.

        Same seam as EngineBase.generate, so the server swaps it in
        transparently.
        """
        msgs = self._augment_system(messages)
        context = {"session_id": session_id,
                   "turns": sum(1 for m in messages
                                if m.get("role") == "user"),
                   "started_at": time.time()}
        agg_stats: dict[str, Any] = {"tokens_generated": 0,
                                     "prompt_tokens": 0}
        started = time.monotonic()
        ttft: float | None = None
        try:
            async for ev in self._run_rounds(request_id, session_id, msgs,
                                             params, context, agg_stats,
                                             started, ttft):
                yield ev
        finally:
            self._active_sub.pop(request_id, None)

    async def _run_rounds(self, request_id: str, session_id: str,
                          msgs: list[dict], params: GenerationParams,
                          context: dict, agg_stats: dict, started: float,
                          ttft: float | None,
                          ) -> AsyncGenerator[dict, None]:
        for round_no in range(self.max_tool_rounds + 1):
            parser = HermesStreamParser()
            raw_text = ""
            calls_this_round = []
            terminal = None
            sub_id = f"{request_id}.t{round_no}"
            self._active_sub[request_id] = sub_id
            agen = self.engine.generate(sub_id, session_id, msgs, params)
            async for event in agen:
                etype = event["type"]
                if etype == "token":
                    raw_text += event["text"]
                    # Split around the first completed call: collect THIS
                    # feed's calls before judging its text (a chunk can
                    # both complete a <tool_call> and carry prose,
                    # ADVICE r3), and stream the prose that PRECEDED the
                    # round's first call even when it arrives in the same
                    # chunk that completes it — chunk boundaries are
                    # arbitrary (ADVICE r4). All completed calls execute
                    # (the reference accumulated every streamed call
                    # before executing, vllm_handler.py:389-412).
                    pre, calls, post = parser.feed_split(event["text"])
                    had_calls = bool(calls_this_round)
                    calls_this_round.extend(calls)
                    if not had_calls and pre:
                        if ttft is None:
                            ttft = (time.monotonic() - started) * 1000
                        yield {"type": "token", "text": pre}
                    if calls_this_round:
                        # Once a tool block exists, no FURTHER text is
                        # forwarded to the client: the round is aborted
                        # and regenerated with the tool results, so
                        # trailing prose would show up as a stray
                        # duplicated fragment. Prose in a LATER chunk
                        # (one that completed no call itself) means the
                        # model moved on past the block — stop the
                        # round and execute what we have.
                        if had_calls and not calls and pre.strip():
                            break
                        continue
                elif etype in ("done", "cancelled", "error"):
                    terminal = event
                    st = event.get("stats", {})
                    # `or 0`: remote backends report None when the
                    # upstream gave no usage accounting.
                    agg_stats["tokens_generated"] += st.get(
                        "tokens_generated") or 0
                    agg_stats["prompt_tokens"] = (
                        st.get("prompt_tokens")
                        or agg_stats["prompt_tokens"])

            if terminal is None:
                # Broke out on a tool call mid-stream: close the stream,
                # which cancels the engine request and frees its slot.
                await agen.aclose()
            else:
                tail = parser.flush()
                if tail and not calls_this_round:
                    # With calls pending the round is aborted and
                    # regenerated — a flushed fragment (e.g. a lone "<"
                    # that looked like a tag opener) must not leak to
                    # the client, same policy as the in-stream
                    # suppression above.
                    yield {"type": "token", "text": tail}
                if terminal["type"] in ("cancelled", "error"):
                    yield self._final(terminal, agg_stats, started, ttft)
                    return
                if not calls_this_round:
                    yield self._final(terminal, agg_stats, started, ttft)
                    return

            if round_no >= self.max_tool_rounds:
                log.warning(f"[{session_id}] tool-round limit reached")
                yield self._final(
                    {"type": "done", "finish_reason": "tool_rounds"},
                    agg_stats, started, ttft)
                return

            # Execute EVERY completed call of the round, concurrently
            # (tools are independent: read-only lookups or idempotent
            # fetches; the registry serialises rate-limited ones
            # itself), then append all results before resuming —
            # matching the reference's accumulate-then-execute-all
            # (vllm_handler.py:389-412).
            for call in calls_this_round:
                self._m_calls.inc()
                yield {"type": "tool_call", "tool": call.name,
                       "arguments": call.arguments}
            results = await asyncio.gather(
                *(self.registry.execute(c.name, c.arguments,
                                        context=context)
                  for c in calls_this_round))
            msgs = msgs + [{"role": "assistant", "content": raw_text}]
            for call, result in zip(calls_this_round, results):
                log.info(f"[{session_id}] tool {call.name} -> "
                         f"{result[:120]}")
                msgs = msgs + [
                    {"role": "tool",
                     "content": format_tool_result(call.name, result)},
                ]

        yield self._final({"type": "done", "finish_reason": "tool_rounds"},
                          agg_stats, started, ttft)

    def _final(self, terminal: dict, agg: dict, started: float,
               ttft: float | None) -> dict:
        dur = time.monotonic() - started
        toks = agg["tokens_generated"]
        out = {
            "type": terminal["type"],
            "finish_reason": terminal.get("finish_reason", "stop"),
            "stats": {
                "tokens_generated": toks,
                "processing_time_ms": dur * 1000,
                "tokens_per_second": toks / dur if dur > 0 else 0.0,
                "ttft_ms": ttft,
                "prompt_tokens": agg.get("prompt_tokens", 0),
            },
        }
        # Error events must keep their payload: the serving layer keys
        # load-shed handling (deadline_expired → retry_after frame /
        # 429, breaker untouched) on `code`, and stripping it here made
        # every agent-path expiry count as a backend failure.
        for key in ("error", "code", "retry_after"):
            if key in terminal:
                out[key] = terminal[key]
        return out

    async def aclose(self) -> None:
        """Release tool resources (search backend HTTP session)."""
        await self.registry.aclose()

    # Engine-seam passthroughs so the agent is substitutable wherever an
    # EngineBase is expected (WS server, OpenAI route).
    def check_connection(self) -> bool:
        return self.engine.check_connection()

    def cancel(self, request_id: str) -> bool:
        sub = self._active_sub.get(request_id)
        return self.engine.cancel(sub or request_id)

    def release_session(self, session_id: str) -> None:
        self.engine.release_session(session_id)

    def get_stats(self) -> dict:
        return self.engine.get_stats()

    def get_model_info(self) -> dict:
        info = dict(self.engine.get_model_info())
        info["tools"] = self.registry.names()
        return info
