"""Streaming parser for hermes-style tool-call markup.

The reference outsourced tool-call parsing to vLLM's server flag
(--tool-call-parser hermes, docker-compose.vllm.yml:50-51) and let
PydanticAI drive the loop (SURVEY.md §3.4). This framework owns the
decode stream, so it parses the markup itself:

    <tool_call>{"name": "get_weather", "arguments": {"city": "Oslo"}}</tool_call>

The parser is incremental: feed it text deltas as they stream; it
returns the user-visible text (with tool-call markup suppressed) and any
completed tool calls. A partial opening tag at the end of a delta is
held back until it can be disambiguated.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

OPEN_TAG = "<tool_call>"
CLOSE_TAG = "</tool_call>"


@dataclass
class ToolCall:
    name: str
    arguments: dict
    raw: str


class HermesStreamParser:
    def __init__(self) -> None:
        self._buf = ""
        self._in_call = False

    def feed(self, delta: str) -> tuple[str, list[ToolCall]]:
        """Consume a text delta; return (emittable_text, completed_calls)."""
        pre, calls, post = self.feed_split(delta)
        return pre + post, calls

    def feed_split(self, delta: str,
                   ) -> tuple[str, list[ToolCall], str]:
        """Consume a text delta; return ``(pre, completed_calls, post)``
        where ``pre`` is the text that streamed BEFORE the first call
        completed in this feed and ``post`` the text after it. When no
        call completes, everything is ``pre``. Callers that suppress
        text once a call exists (the agent loop) need the split —
        chunk boundaries are arbitrary, so prose preceding a call can
        arrive in the very chunk that completes it (ADVICE r4)."""
        self._buf += delta
        pre: list[str] = []
        post: list[str] = []
        calls: list[ToolCall] = []
        while True:
            out = post if calls else pre
            if self._in_call:
                end = self._buf.find(CLOSE_TAG)
                if end < 0:
                    return "".join(pre), calls, "".join(post)
                raw = self._buf[:end]
                self._buf = self._buf[end + len(CLOSE_TAG):]
                self._in_call = False
                calls.append(self._parse(raw))
            else:
                start = self._buf.find(OPEN_TAG)
                if start >= 0:
                    out.append(self._buf[:start])
                    self._buf = self._buf[start + len(OPEN_TAG):]
                    self._in_call = True
                    continue
                # Hold back any suffix that is a prefix of the open tag.
                hold = 0
                for k in range(min(len(OPEN_TAG) - 1, len(self._buf)), 0, -1):
                    if self._buf.endswith(OPEN_TAG[:k]):
                        hold = k
                        break
                cut = len(self._buf) - hold
                out.append(self._buf[:cut])
                self._buf = self._buf[cut:]
                return "".join(pre), calls, "".join(post)

    def flush(self) -> str:
        """End of stream: release held-back text. An unterminated tool
        call body is dropped (it never completed) — and so is a held
        partial OPENING tag of two or more characters ("<tool_cal" at
        a max_tokens cutoff): the in-stream path holds such a suffix
        back waiting for the rest of the tag, and releasing it here
        leaked raw markup into user-visible text whenever the stream
        ended mid-tag. A lone trailing "<" is still released —
        legitimate prose ends with it far more often than a tag
        starts one character before the end of a stream."""
        if self._in_call:
            text = ""
        else:
            text = self._buf
            for k in range(min(len(OPEN_TAG) - 1, len(text)), 1, -1):
                if text.endswith(OPEN_TAG[:k]):
                    text = text[:-k]
                    break
        self._buf = ""
        self._in_call = False
        return text

    @staticmethod
    def _parse(raw: str) -> ToolCall:
        try:
            obj = json.loads(raw.strip())
            name = obj.get("name", "")
            args = obj.get("arguments", {})
            if isinstance(args, str):  # some models emit stringified args
                args = json.loads(args) if args else {}
            if not isinstance(args, dict):
                args = {"value": args}
            return ToolCall(name=name, arguments=args, raw=raw)
        except (json.JSONDecodeError, AttributeError):
            return ToolCall(name="", arguments={}, raw=raw)


def format_tool_result(name: str, result: str) -> str:
    """Result message body in hermes convention."""
    return f"<tool_response>\n{json.dumps({'name': name, 'content': result})}\n</tool_response>"


def inject_tools_section(messages: list[dict], section: str) -> list[dict]:
    """Merge a tools section into the conversation's system prompt
    (append to an existing leading system message, else insert one).
    Shared by the agent loop and the OpenAI route so the placement rule
    can't drift between them."""
    msgs = [dict(m) for m in messages]
    if msgs and msgs[0].get("role") == "system":
        msgs[0]["content"] = msgs[0]["content"] + "\n\n" + section
    else:
        msgs.insert(0, {"role": "system", "content": section})
    return msgs


def tools_system_prompt(tool_specs: list[dict]) -> str:
    """System-prompt section teaching the model the hermes call format."""
    lines = [
        "You have access to the following tools. To call a tool, emit "
        "exactly:",
        '<tool_call>{"name": "<tool_name>", "arguments": {...}}</tool_call>',
        "Tool results arrive in <tool_response> messages. "
        "Use tools only when needed, then answer the user.",
        "Available tools:",
    ]
    for spec in tool_specs:
        lines.append(json.dumps(spec))
    return "\n".join(lines)
