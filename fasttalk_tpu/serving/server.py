"""WebSocket + HTTP serving layer on aiohttp.

Speaks the exact JSON protocol of the reference server so existing
clients work unchanged (message list at websocket_server_vllm.py:314-340
and README.md:234-319; token frames carry the delta in "data" as at
websocket_server_vllm.py:495):

  client→server: start_session, user_message, cancel, end_session,
                 update_config
  server→client: session_started, session_configured, token,
                 response_complete, cancelled, session_ended,
                 config_updated, error

plus HTTP GET /, /health, /stats, /models on the same port
(websocket_server_vllm.py:140-213).

Deliberate fixes over the reference (SURVEY.md known-flaws list):
- generation runs as an asyncio.Task, so `cancel` is receivable
  mid-generation (reference processed it only after generation ended);
- per-session config from start_session/update_config is stored AND
  applied to generation (reference silently dropped it);
- the circuit breaker actually wraps the engine call;
- true tokenizer token counts in stats.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any

from aiohttp import WSCloseCode, WSMsgType, web

from fasttalk_tpu import __version__
from fasttalk_tpu.engine.engine import EngineBase, GenerationParams
from fasttalk_tpu.observability.journey import JourneyRecorder
from fasttalk_tpu.observability.perf import get_perf
from fasttalk_tpu.observability.slo import get_slo
from fasttalk_tpu.observability.trace import (bind_request,
                                              current_trace_id,
                                              get_tracer, mint_trace_id,
                                              parse_traceparent)
from fasttalk_tpu.observability.watchdog import get_watchdog
from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.serving.connection import ConnectionManager, ConnectionState
from fasttalk_tpu.serving.conversation import ConversationManager
from fasttalk_tpu.serving.text_processor import extract_speakable_chunk
from fasttalk_tpu.utils.config import Config
from fasttalk_tpu.utils.errors import (
    ENGINE_SHED_CODES,
    AdmissionRejected,
    CircuitBreaker,
    CircuitBreakerOpen,
    ErrorCategory,
    ErrorHandler,
    LLMServiceError,
)
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("serving.server")


class WebSocketLLMServer:
    def __init__(self, config: Config, engine: EngineBase,
                 agent: Any | None = None):
        self.config = config
        self.engine = engine
        self.agent = agent  # optional VoiceAgent (tool-calling path)
        self.connection_manager = ConnectionManager(
            max_connections=config.max_connections,
            idle_timeout=config.session_timeout)
        count = None
        tokenizer = getattr(engine, "tokenizer", None)
        if tokenizer is not None:
            count = lambda s: len(tokenizer.encode(s))  # noqa: E731
        self.conversation_manager = ConversationManager(
            count_tokens=count,
            max_history_tokens=max(256, config.default_context_window
                                   - config.default_max_tokens),
            session_timeout=config.session_timeout,
            default_system_prompt=config.system_prompt or None)
        self.error_handler = ErrorHandler()
        self.breaker = CircuitBreaker()
        self._gen_tasks: dict[str, asyncio.Task] = {}
        self._cur_request: dict[str, str] = {}
        self._housekeeping: asyncio.Task | None = None
        # Stall watchdog (observability/watchdog.py): heartbeats the
        # engine step loop, flags token-stalled requests, cancels the
        # hopeless ones with a proper terminal error, and degrades
        # /health. Duck-typed — engines without the progress surfaces
        # (FakeEngine, remote providers) are simply unwatched.
        self.watchdog = get_watchdog()
        self.watchdog.bind_engine(engine)
        self._watchdog_task: asyncio.Task | None = None
        # Flight recorder (observability/flight.py): subscribe to the
        # event log so SLO pages, stalls, restarts and recompile
        # bursts snapshot their evidence (events/traces/metrics/perf/
        # config) the moment they are detected — no by-hand repro
        # before /profiler/start is useful.
        from fasttalk_tpu.observability.flight import get_flight

        get_flight().install()
        # Continuous host profiler (observability/profiler.py): samples
        # host thread stacks so /debug/profile and the host_gap_causes
        # block on /perf can name where non-device time goes. start()
        # is a no-op (no thread) when PROF_ENABLED=false.
        from fasttalk_tpu.observability.profiler import get_profiler

        get_profiler().start()
        m = get_metrics()
        self._m_ws_tokens = m.counter("ws_tokens_streamed_total",
                                      "token frames streamed to clients")
        self._m_ws_send = m.histogram(
            "ws_send_ms", "WebSocket frame send wall time (request-"
            "correlated frames only)",
            buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250,
                     1000))
        # Serving spans carry component="serving" so stitched fleet
        # traces (observability/stitch.py) keep the edge's ws_send /
        # token_journey rows apart from router and replica spans.
        self._tracer = get_tracer().scoped("serving")

        # client_max_size: the KV migration import (/kv/parked POST)
        # carries a whole parked session's rows — tens of MB for long
        # contexts, far past aiohttp's 1 MB default. Only raised when
        # the channel is actually served.
        kv_http = bool(getattr(config, "kv_migrate_http", False))
        self.app = web.Application(
            client_max_size=(256 * 1024 * 1024) if kv_http
            else 1024 ** 2)
        self.app.router.add_get("/", self._http_root)
        self.app.router.add_get("/health", self._http_health)
        self.app.router.add_get("/stats", self._http_stats)
        self.app.router.add_get("/models", self._http_models)
        # Serving-port observability surfaces (docs/OBSERVABILITY.md
        # "Fleet tracing"): the router reaches a REMOTE replica only
        # through this port, so the registry exposition, the SLO
        # report and the replica's trace fragments must be served here
        # too (the monitoring port may not be routable fleet-wide).
        self.app.router.add_get("/metrics", self._http_metrics)
        self.app.router.add_get("/slo", self._http_slo)
        self.app.router.add_get("/traces/{request_id}",
                                self._http_trace)
        self.app.router.add_get("/ws/llm", self.handle_websocket)
        # Router-backed mode (docs/ROUTER.md): when the engine is a
        # FleetRouter, expose the fleet registry and the coordinated
        # single-replica drain used for rolling restarts.
        self.fleet_flight = None
        if hasattr(engine, "fleet_stats"):
            self.app.router.add_get("/fleet", self._http_fleet)
            self.app.router.add_post("/fleet/drain/{replica_id}",
                                     self._http_fleet_drain)
        if hasattr(engine, "fleet_metrics"):
            self.app.router.add_get("/fleet/metrics",
                                    self._http_fleet_metrics)
            self.app.router.add_get("/fleet/slo", self._http_fleet_slo)
            # Fleet flight recorder (observability/fleetflight.py):
            # router-side incident triggers fan evidence collection out
            # to every live replica into one bundle directory.
            from fasttalk_tpu.observability.fleetflight import \
                FleetFlightRecorder

            self.fleet_flight = FleetFlightRecorder(
                engine,
                enabled=getattr(config, "fleet_flight_enabled", True),
                base_dir=getattr(config, "fleet_flight_dir", None),
                max_bundles=getattr(config, "fleet_flight_max_bundles",
                                    None),
                min_interval_s=getattr(config,
                                       "fleet_flight_min_interval_s",
                                       None),
                failover_burst=getattr(config,
                                       "fleet_flight_failover_burst",
                                       None),
                window_s=getattr(config, "fleet_flight_window_s",
                                 None))
            self.fleet_flight.install()
        # Cross-replica KV migration channel (docs/ROUTER.md,
        # router/migrate.py): a remote router moves parked session KV
        # in and out of THIS replica's host pool through these. Engines
        # without a pool answer 404/409 via the EngineBase defaults.
        # Gated by KV_MIGRATE_HTTP (default off): the serving port is
        # unauthenticated and the export side returns a session's
        # token ids — only replicas whose port is reachable solely
        # from the router network may serve it.
        if kv_http:
            self.app.router.add_get("/kv/parked/{session_id}",
                                    self._http_kv_export)
            self.app.router.add_post("/kv/parked/{session_id}",
                                     self._http_kv_import)
            self.app.router.add_delete("/kv/parked/{session_id}",
                                       self._http_kv_release)
        from fasttalk_tpu.serving.openai_api import register_openai_routes

        register_openai_routes(
            self.app,
            backend=lambda: self.agent if self.agent is not None
            else self.engine,
            model_name=self._model_name,
            defaults={"temperature": config.default_temperature,
                      "top_p": config.default_top_p,
                      "top_k": config.default_top_k,
                      "max_tokens": config.default_max_tokens,
                      "repeat_penalty": config.default_repeat_penalty,
                      "presence_penalty": config.default_presence_penalty,
                      "frequency_penalty":
                          config.default_frequency_penalty,
                      "priority": config.sched_default_priority},
            breaker=self.breaker)
        self.app.on_startup.append(self._on_startup)
        self.app.on_cleanup.append(self._on_cleanup)

    # ---------------- lifecycle ----------------

    async def _on_startup(self, app: web.Application) -> None:
        self._housekeeping = asyncio.create_task(self._housekeep())
        self._watchdog_task = asyncio.create_task(self.watchdog.run())

    async def _on_cleanup(self, app: web.Application) -> None:
        if self._housekeeping:
            self._housekeeping.cancel()
        if self._watchdog_task:
            self._watchdog_task.cancel()
        if self.fleet_flight is not None:
            self.fleet_flight.uninstall()
        # Graceful drain (docs/SCHEDULING.md): new submissions are
        # rejected with retry_after from here on, while generations
        # already streaming (or queued) get up to the drain timeout to
        # finish before being cancelled.
        self.engine.begin_drain()
        pending = [t for t in self._gen_tasks.values() if not t.done()]
        if pending and self.config.sched_drain_timeout_s > 0:
            await asyncio.wait(pending,
                               timeout=self.config.sched_drain_timeout_s)
        for task in list(self._gen_tasks.values()):
            task.cancel()

    async def _housekeep(self) -> None:
        """Periodic idle-session GC — actually scheduled, unlike the
        reference's cleanup_idle_sessions (SURVEY.md §5)."""
        while True:
            await asyncio.sleep(60)
            try:
                self.conversation_manager.cleanup_idle_sessions()
                for sid in self.connection_manager.idle_sessions():
                    info = self.connection_manager.get_connection(sid)
                    if info is not None:
                        log.info(f"[{sid}] closing idle connection")
                        await info.websocket.close()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.error(f"housekeeping error: {e}")

    # ---------------- HTTP ----------------


    def _backend(self):
        """The generation backend the server talks to: agent when
        enabled (same seam), bare engine otherwise."""
        return self.agent if self.agent is not None else self.engine

    def _model_name(self) -> str:
        try:
            return self.engine.get_model_info().get("model",
                                                    self.config.model_name)
        except Exception:
            return self.config.model_name

    async def _http_root(self, request: web.Request) -> web.Response:
        return web.json_response({
            "service": "FastTalk-TPU LLM Service",
            "status": "ready",
            "version": __version__,
            "provider": self.config.llm_provider,
            "model": self._model_name(),
            "agent_enabled": self.agent is not None,
            "web_search_enabled": self.config.enable_web_search,
            "tools_enabled": self.config.enable_tools,
        })

    async def _http_health(self, request: web.Request) -> web.Response:
        try:
            # to_thread: remote-backend engines may do a blocking probe.
            ok = await asyncio.to_thread(self.engine.check_connection)
            body = {
                "status": "healthy" if ok else "degraded",
                "provider": self.config.llm_provider,
                "model": self._model_name(),
                "backend_connection": ok,
                "agent_enabled": self.agent is not None,
                "active_connections":
                    self.connection_manager.get_active_count(),
                "active_sessions":
                    self.conversation_manager.get_session_count(),
                "circuit_breaker": self.breaker.to_dict(),
            }
            # Overload state machine (docs/SCHEDULING.md): load
            # balancers and operators see pressured/shedding/draining
            # before the cliff. "healthy" stays 200; overload states
            # are reported but don't flip the status code — the server
            # is still serving (that is the whole point of shedding).
            engine_stats = self.engine.get_stats()
            sched = engine_stats.get("scheduler")
            if sched is not None:
                body["scheduler"] = sched
                if sched.get("state") != "healthy":
                    body["status"] = sched["state"]
            # Router-backed mode: load balancers watching this port see
            # the fleet's placement capacity, not just liveness — a
            # fleet with dead replicas still serves (that is the whole
            # point of failover), but operators must see it shrink.
            fleet = engine_stats.get("router")
            if fleet is not None:
                body["fleet"] = fleet
                # Degrade on DEATH only: a draining replica (rolling
                # restart) also isn't placeable, but that is planned —
                # paging a load balancer on every drain would punish
                # the operator for using the drain endpoint.
                if fleet.get("dead", 0) > 0:
                    body["status"] = "degraded"
            # Watchdog + SLO burn state (docs/OBSERVABILITY.md): a hung
            # engine step, token-stalled requests, or a page-level SLO
            # burn all degrade the serving-port health too — load
            # balancers watching this port must see them. Still 200:
            # the server itself is reachable and serving.
            wd = self.watchdog.status()
            if not wd["ok"]:
                body["status"] = "degraded"
                body["watchdog"] = wd
            slo = get_slo().alert_summary()
            if slo:
                body["slo"] = slo
                if any(state == "page" for state in slo.values()):
                    body["status"] = "degraded"
            return web.json_response(body, status=200 if ok else 503)
        except Exception as e:
            return web.json_response({"status": "unhealthy", "error": str(e)},
                                     status=503)

    async def _http_stats(self, request: web.Request) -> web.Response:
        m = get_metrics()
        return web.json_response({
            "connections": self.connection_manager.get_statistics(),
            "conversations": self.conversation_manager.get_statistics(),
            "errors": self.error_handler.get_error_stats(),
            "engine": self.engine.get_stats(),
            "lifetime": {  # process-lifetime totals (survive disconnects)
                "tokens_generated":
                    m.counter("engine_tokens_generated_total").value,
                "requests": m.counter("engine_requests_total").value,
                "ttft_ms": m.histogram("engine_ttft_ms").summary(),
                "uptime_seconds": m.uptime(),
            },
            "provider": self.config.llm_provider,
        })

    async def _http_models(self, request: web.Request) -> web.Response:
        try:
            source = self.agent if self.agent is not None else self.engine
            return web.json_response(source.get_model_info())
        except Exception as e:
            return web.json_response({"error": str(e)})

    async def _http_fleet(self, request: web.Request) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(self.engine.fleet_stats))

    async def _http_fleet_drain(self, request: web.Request,
                                ) -> web.Response:
        replica_id = request.match_info["replica_id"]
        try:
            summary = await asyncio.to_thread(self.engine.drain_replica,
                                              replica_id)
        except KeyError:
            return web.json_response(
                {"error": f"unknown replica {replica_id!r}"}, status=404)
        return web.json_response(summary)

    # -------- serving-port observability (docs/OBSERVABILITY.md) ----

    async def _http_metrics(self, request: web.Request) -> web.Response:
        text = await asyncio.to_thread(get_metrics().prometheus)
        return web.Response(text=text,
                            content_type="text/plain; version=0.0.4",
                            charset="utf-8")

    async def _http_slo(self, request: web.Request) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(get_slo().snapshot))

    async def _http_trace(self, request: web.Request) -> web.Response:
        """This process's trace fragments for a request — and, when
        the engine is a FleetRouter, the stitched cross-replica
        timeline. Remote replicas answer the router's fan-out through
        this same route (router/replica.py fetch_trace reads
        ``fragments``)."""
        from fasttalk_tpu.observability.stitch import (collect_fragments,
                                                       stitch)

        request_id = request.match_info["request_id"]
        trace_id = request.query.get("trace_id", "")

        def build() -> dict[str, Any]:
            frags = collect_fragments(get_tracer(), request_id,
                                      trace_id)
            body: dict[str, Any] = {"request_id": request_id,
                                    "fragments": frags}
            if hasattr(self.engine, "stitched_trace"):
                stitched = self.engine.stitched_trace(request_id)
                if stitched is not None:
                    body["stitched"] = stitched
            elif frags:
                body["stitched"] = stitch(frags)
            return body

        body = await asyncio.to_thread(build)
        if not body.get("fragments") and not body.get("stitched"):
            return web.json_response(
                {"error": f"no trace for {request_id}"}, status=404)
        return web.json_response(
            body, dumps=lambda o: json.dumps(o, default=str))

    async def _http_fleet_metrics(self, request: web.Request,
                                  ) -> web.Response:
        text = await asyncio.to_thread(self.engine.fleet_metrics)
        return web.Response(text=text,
                            content_type="text/plain; version=0.0.4",
                            charset="utf-8")

    async def _http_fleet_slo(self, request: web.Request,
                              ) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(self.engine.fleet_slo),
            dumps=lambda o: json.dumps(o, default=str))

    # ---------------- KV migration channel ----------------

    def _kv_wire_step(self, name: str, session_id: str,
                      request: web.Request) -> None:
        """Record a migration wire hop against the originating trace.
        The router sends ``traceparent`` on /kv/parked requests
        (router/migrate.py transfer); there is no local request trace
        for the session here, so the hop lands as a step record
        carrying the trace id — scripts/trace_report.py and the
        stitched timeline pick it up by trace id."""
        parsed = parse_traceparent(
            request.headers.get("traceparent", ""))
        if parsed is None:
            return
        t = time.monotonic()
        self._tracer.step(name, t, t, session_id=session_id,
                          trace_id=parsed)

    async def _http_kv_export(self, request: web.Request,
                              ) -> web.Response:
        session_id = request.match_info["session_id"]
        self._kv_wire_step("kv_export", session_id, request)
        if request.query.get("meta"):
            info = await asyncio.to_thread(self.engine.parked_kv_info,
                                           session_id)
            if info is None:
                return web.json_response(
                    {"error": "no parked entry"}, status=404)
            return web.json_response({"session_id": session_id,
                                      "kept": info[0],
                                      "nbytes": info[1]})
        entry = await asyncio.to_thread(self.engine.export_parked_kv,
                                        session_id)
        if entry is None:
            return web.json_response({"error": "no parked entry"},
                                     status=404)
        from fasttalk_tpu.router.migrate import serialize_parked

        data = await asyncio.to_thread(serialize_parked, entry)
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def _http_kv_import(self, request: web.Request,
                              ) -> web.Response:
        from fasttalk_tpu.router.migrate import deserialize_parked

        session_id = request.match_info["session_id"]
        self._kv_wire_step("kv_import", session_id, request)
        data = await request.read()
        try:
            entry = await asyncio.to_thread(deserialize_parked, data)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        if entry.session_id != session_id:
            return web.json_response(
                {"error": f"entry is for session "
                 f"{entry.session_id!r}, not {session_id!r}"},
                status=400)
        ok = await asyncio.to_thread(self.engine.import_parked_kv,
                                     entry)
        if not ok:
            return web.json_response(
                {"error": "entry refused (pool disabled, over budget, "
                 "or cache-geometry mismatch)"}, status=409)
        return web.json_response({"imported": True,
                                  "session_id": session_id,
                                  "kept": entry.kept,
                                  "nbytes": entry.nbytes})

    async def _http_kv_release(self, request: web.Request,
                               ) -> web.Response:
        session_id = request.match_info["session_id"]
        ok = await asyncio.to_thread(self.engine.drop_parked_kv,
                                     session_id)
        if not ok:
            return web.json_response({"error": "no parked entry"},
                                     status=404)
        return web.json_response({"released": True,
                                  "session_id": session_id})

    # ---------------- WebSocket ----------------

    async def handle_websocket(self, request: web.Request,
                               ) -> web.WebSocketResponse:
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        session_id = str(uuid.uuid4())
        log.log_connection(session_id, "opened")

        info = self.connection_manager.add_connection(session_id, ws)
        if info is None:
            # Counted in ws_connections_rejected_total (connection.py).
            # The frame carries a retry_after hint and the close uses
            # the standard 1013 "try again later" code, so clients can
            # tell capacity rejection from a protocol error and back
            # off instead of hot-reconnecting.
            retry_after = self.connection_manager.retry_after_hint()
            await ws.send_json({
                "type": "error",
                "error": {"code": "max_connections",
                          "message": "Maximum connections reached",
                          "severity": "high",
                          "retry_after": retry_after},
            })
            await ws.close(code=WSCloseCode.TRY_AGAIN_LATER,
                           message=b"max connections; retry later")
            return ws

        try:
            await self._send(session_id, ws, {
                "type": "session_started",
                "session_id": session_id,
                "provider": self.config.llm_provider,
                "model": self._model_name(),
                "agent_enabled": self.agent is not None,
            })
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    self.connection_manager.record_message_received(session_id)
                    await self._dispatch(session_id, msg.data, ws)
                elif msg.type in (WSMsgType.ERROR, WSMsgType.CLOSE):
                    break
        finally:
            task = self._gen_tasks.pop(session_id, None)
            if task is not None:
                task.cancel()
            rid = self._cur_request.pop(session_id, None)
            if rid is not None:
                self._backend().cancel(rid)
            self._backend().release_session(session_id)
            self.connection_manager.remove_connection(session_id)
            self.conversation_manager.end_session(session_id)
            log.log_connection(session_id, "closed", level="debug")
        return ws

    async def _send(self, session_id: str, ws: web.WebSocketResponse,
                    payload: dict, request_id: str | None = None) -> None:
        """Send one frame; when request-correlated, time the send into
        the ws_send_ms histogram and the request's trace (backpressure
        from a slow client shows up exactly here)."""
        if ws.closed:
            return
        if _fp.enabled:
            # Chaos seam: `error` simulates a peer reset mid-send (the
            # stream teardown must cancel the generation and free the
            # slot); `corrupt` delivers a non-JSON text frame — what a
            # half-written proxy buffer looks like to the client.
            # fire_async: delay/hang here must stall THIS stream, not
            # the whole event loop.
            if await _fp.fire_async(
                    "serving.ws.send", exc=ConnectionResetError,
                    session_id=session_id,
                    request_id=request_id or "") == "corrupt":
                await ws.send_str("\x00corrupt-frame\x00")
                return
        if request_id is not None:
            t0 = time.monotonic()
            await ws.send_json(payload)
            t1 = time.monotonic()
            self._m_ws_send.observe((t1 - t0) * 1000)
            self._tracer.add_span(request_id, "ws_send", t0, t1,
                                  frame=payload.get("type"))
        else:
            await ws.send_json(payload)
        self.connection_manager.record_message_sent(session_id)

    async def _send_error(self, session_id: str, ws: web.WebSocketResponse,
                          code: str, message: str, **extra: Any) -> None:
        await self._send(session_id, ws, {
            "type": "error",
            "error": {"code": code, "message": message, **extra},
        })

    async def _dispatch(self, session_id: str, raw: str,
                        ws: web.WebSocketResponse) -> None:
        try:
            message = json.loads(raw)
        except json.JSONDecodeError:
            await self._send_error(session_id, ws, "invalid_json",
                                   "Invalid JSON format")
            return
        msg_type = message.get("type")
        try:
            if msg_type == "start_session":
                await self._handle_start_session(session_id, message, ws)
            elif msg_type == "user_message":
                await self._handle_user_message(session_id, message, ws)
            elif msg_type == "cancel":
                await self._handle_cancel(session_id, ws)
            elif msg_type == "end_session":
                await self._handle_end_session(session_id, ws)
            elif msg_type == "update_config":
                await self._handle_update_config(session_id, message, ws)
            else:
                await self._send_error(session_id, ws, "unknown_message_type",
                                       f"Unknown message type: {msg_type}")
        except Exception as e:
            log.error(f"[{session_id}] error handling {msg_type}: {e}",
                      exc_info=True)
            self.connection_manager.record_error(session_id)
            err = self.error_handler.handle_error(e, {"session_id": session_id})
            await self._send(session_id, ws, {"type": "error",
                                              "error": err.to_dict()})

    # Generation-config keys a client may set per session; anything else
    # in the config blob is stored for echo but never splatted inward.
    _GEN_KEYS = ("temperature", "top_p", "top_k", "max_tokens", "stop",
                 "tts_chunking", "repeat_penalty", "presence_penalty",
                 "frequency_penalty", "ignore_eos", "priority",
                 "deadline_s", "structured", "journey")

    @classmethod
    def _gen_overrides(cls, cfg: dict) -> dict:
        out = {k: cfg[k] for k in cls._GEN_KEYS if k in cfg}
        if isinstance(out.get("stop"), str):
            out["stop"] = [out["stop"]]  # a bare string is one stop seq
        return out

    async def _handle_start_session(self, session_id: str, message: dict,
                                    ws: web.WebSocketResponse) -> None:
        cfg = message.get("config", {}) or {}
        system_prompt = cfg.get("system_prompt", self.config.system_prompt)
        self.conversation_manager.create_session(
            session_id, system_prompt=system_prompt,
            gen_config=self._gen_overrides(cfg))
        info = self.connection_manager.get_connection(session_id)
        if info is not None:
            info.config = dict(cfg)
        await self._send(session_id, ws, {
            "type": "session_configured",
            "config": cfg,
            "provider": self.config.llm_provider,
        })

    async def _handle_user_message(self, session_id: str, message: dict,
                                   ws: web.WebSocketResponse) -> None:
        text = message.get("text", "")
        if not text:
            await self._send_error(session_id, ws, "empty_message",
                                   "Empty user message")
            return
        if session_id in self._gen_tasks \
                and not self._gen_tasks[session_id].done():
            await self._send_error(
                session_id, ws, "generation_in_progress",
                "A generation is already running for this session; "
                "cancel it first")
            return
        self.conversation_manager.add_user_message(session_id, text)
        self.connection_manager.update_connection_state(
            session_id, ConnectionState.PROCESSING)
        # Run as a task so cancel/end messages stay receivable mid-stream.
        self._gen_tasks[session_id] = asyncio.create_task(
            self._generate(session_id, text, ws))

    def _gen_params(self, session_id: str) -> GenerationParams:
        state = self.conversation_manager.get(session_id)
        over = state.gen_config if state else {}
        stop = over.get("stop", [])
        if isinstance(stop, str):
            stop = [stop]
        ignore_eos = over.get("ignore_eos", False)
        if not isinstance(ignore_eos, bool):
            # Strict: bool("false") is True — a stringly-typed client
            # value must 400/invalid_config like every other bad knob,
            # not silently decode every reply to the full budget.
            raise ValueError(
                f"ignore_eos must be a boolean, got {ignore_eos!r}")
        journey = over.get("journey", False)
        if not isinstance(journey, bool):
            raise ValueError(
                f"journey must be a boolean, got {journey!r}")
        # Server-side kill switch: JOURNEY_ENABLED=false ignores the
        # per-session opt-in without erroring the client.
        journey = journey and getattr(self.config, "journey_enabled",
                                      True)
        return GenerationParams(
            temperature=float(over.get("temperature",
                                       self.config.default_temperature)),
            top_k=int(over.get("top_k", self.config.default_top_k)),
            top_p=float(over.get("top_p", self.config.default_top_p)),
            max_tokens=int(over.get("max_tokens",
                                    self.config.default_max_tokens)),
            stop=[s for s in stop if isinstance(s, str) and s],
            repeat_penalty=float(over.get(
                "repeat_penalty", self.config.default_repeat_penalty)),
            presence_penalty=float(over.get(
                "presence_penalty", self.config.default_presence_penalty)),
            frequency_penalty=float(over.get(
                "frequency_penalty",
                self.config.default_frequency_penalty)),
            ignore_eos=ignore_eos,
            # Admission-control knobs (docs/SCHEDULING.md): priority
            # class and queue deadline, settable per session/request
            # (GenerationParams validates both — bad values surface as
            # invalid_config, not a 500).
            priority=str(over.get("priority",
                                  self.config.sched_default_priority)),
            deadline_s=(float(over["deadline_s"])
                        if over.get("deadline_s") is not None else None),
            # Constrained decoding (docs/STRUCTURED.md): the session's
            # "structured" config key ({"kind": "json_object" |
            # "json_schema" | "regex" | "tool_call", ...}); shape
            # errors surface as invalid_config via GenerationParams.
            structured=over.get("structured"),
            # Per-token journey attribution (docs/OBSERVABILITY.md
            # "the token journey"): the engine stamps device-retire /
            # fetch / detokenize monotonics on each token event.
            journey=journey,
        )

    async def _generate(self, session_id: str, user_text: str,
                        ws: web.WebSocketResponse) -> None:
        request_id = f"{session_id}:{uuid.uuid4().hex[:8]}"
        self._cur_request[session_id] = request_id
        # The serving layer owns the request trace (the engine only adds
        # spans to it) and binds the id into the logging ContextVar so
        # every log line of this generation carries it. The WS edge is
        # the trace ROOT: it mints the fleet-wide trace id that rides
        # every downstream hop (router placement, /kv/parked migration,
        # remote-replica dispatch) so GET /traces/{request_id} can
        # stitch one cross-replica timeline (docs/OBSERVABILITY.md).
        tid = current_trace_id() or mint_trace_id()
        self._tracer.start(request_id, session_id, trace_id=tid)
        with bind_request(request_id, trace_id=tid):
            try:
                await self._generate_traced(session_id, user_text, ws,
                                            request_id)
            finally:
                # Terminal marker: exactly ONE per stitched trace — the
                # edge that owns the client stream emits it, inner hops
                # (router-dispatched /v1 legs) never do. stitch()
                # counts these to prove a failed-over request finished
                # exactly once.
                self._tracer.event(request_id, "request_complete")
                self._tracer.finish(request_id)

    async def _generate_traced(self, session_id: str, user_text: str,
                               ws: web.WebSocketResponse,
                               request_id: str) -> None:
        start = time.monotonic()
        full_text = ""
        stats: dict[str, Any] = {}
        state = self.conversation_manager.get(session_id)
        tts = bool(state.gen_config.get("tts_chunking")) if state else False
        tts_buffer = ""
        jr: JourneyRecorder | None = None
        try:
            # Params validation BEFORE touching the breaker: a client
            # that stored an invalid generation config (e.g.
            # repeat_penalty 0) is a client-shape error — it must not
            # count as a backend failure, or one misconfigured client
            # would open the shared breaker for every session (the /v1
            # route draws the same line with _BadRequest → 400).
            try:
                params = self._gen_params(session_id)
            except (TypeError, ValueError) as e:
                self.connection_manager.record_error(session_id)
                await self._send_error(session_id, ws, "invalid_config",
                                       str(e))
                return
            if params.structured is not None:
                # Structured-support probe BEFORE the breaker, mirror
                # of the /v1 route's 400: an engine that cannot serve
                # constraints (mesh, Pallas attention, disabled) is a
                # client-visible config clash, not a backend failure.
                reason = getattr(self.engine, "structured_reason", None)
                if reason is not None:
                    self.connection_manager.record_error(session_id)
                    await self._send_error(
                        session_id, ws, "invalid_config",
                        f"structured output unavailable: {reason}")
                    return
            self.breaker.check()
            if params.journey:
                # Per-token journey waterfall: the engine stamps
                # device-retire/fetch/detokenize monotonics on each
                # token event ("j"); the loop below adds event-loop
                # dequeue and WS-write times so every hop from device
                # step to socket is named (docs/OBSERVABILITY.md).
                jr = JourneyRecorder(start)
            messages = self.conversation_manager.get_messages_for_generation(
                session_id)
            if self.agent is not None:
                stream = self.agent.generate(request_id, session_id,
                                             messages, params)
            else:
                stream = self.engine.generate(request_id, session_id,
                                              messages, params)
            cancelled = False
            finish_reason = "stop"
            async for event in stream:
                etype = event["type"]
                if etype == "token":
                    t_dq = time.monotonic()  # event-loop dequeue mark
                    full_text += event["text"]
                    if tts:
                        tts_buffer += event["text"]
                        chunk, tts_buffer = extract_speakable_chunk(tts_buffer)
                        if chunk:
                            frame = {"type": "token", "data": chunk,
                                     "speakable": True}
                            if jr is not None:
                                # Server wall clock on the frame lets
                                # the client estimate network RTT /
                                # clock offset (client.py --journey).
                                frame["st"] = time.time()
                            await self._send(session_id, ws, frame,
                                             request_id=request_id)
                            self._m_ws_tokens.inc()
                            if jr is not None:
                                jr.frame(event.get("j"), t_dq,
                                         time.monotonic())
                    else:
                        frame = {"type": "token",
                                 "data": event["text"]}
                        if jr is not None:
                            frame["st"] = time.time()
                        await self._send(session_id, ws, frame,
                                         request_id=request_id)
                        self._m_ws_tokens.inc()
                        if jr is not None:
                            jr.frame(event.get("j"), t_dq,
                                     time.monotonic())
                elif etype in ("done", "cancelled"):
                    stats = event.get("stats", {})
                    cancelled = etype == "cancelled"
                    finish_reason = event.get("finish_reason", "stop")
                elif etype == "tool_call":
                    await self._send(session_id, ws, {
                        "type": "tool_call", "tool": event.get("tool"),
                        "arguments": event.get("arguments")},
                        request_id=request_id)
                elif etype == "resumed":
                    # Fleet failover (docs/ROUTER.md): the stream moved
                    # to a surviving replica mid-generation. Informative,
                    # not an error — tokens keep flowing after it.
                    await self._send(session_id, ws, {
                        "type": "resumed",
                        "replica": event.get("replica"),
                        "attempt": event.get("attempt")},
                        request_id=request_id)
                elif etype == "error":
                    if event.get("code") in ENGINE_SHED_CODES:
                        # Queue-deadline expiry / KV block-pool
                        # exhaustion is load shedding, not a backend
                        # fault: surface it like a shed (frame keeps
                        # retry_after; breaker untouched).
                        raise AdmissionRejected.from_shed_event(event)
                    if event.get("code") == "stalled":
                        # Watchdog-terminated (observability/watchdog
                        # .py force_fail): a genuine backend fault —
                        # the breaker counts it — but the frame keeps
                        # the engine's "stalled" code so clients can
                        # tell a hung backend from a model error.
                        self.breaker.record_failure()
                        self.connection_manager.record_error(session_id)
                        await self._send(session_id, ws, {
                            "type": "error",
                            "error": {"code": "stalled",
                                      "message": event.get("error", ""),
                                      "severity": "high",
                                      "recoverable": True}},
                            request_id=request_id)
                        return
                    raise LLMServiceError(event.get("error", "engine error"))
            if tts and tts_buffer:
                await self._send(session_id, ws, {
                    "type": "token", "data": tts_buffer,
                    "speakable": True}, request_id=request_id)
            self.breaker.record_success()
            # Remote backends report tokens_generated=None when the
            # upstream supplied no usage accounting (chunks are not
            # tokens — SURVEY.md §5); counters then record 0 rather
            # than a wrong-unit chunk count.
            tokens = int(stats.get("tokens_generated") or 0)
            self.conversation_manager.add_assistant_message(
                session_id, full_text, tokens_generated=tokens)
            self.connection_manager.record_tokens_generated(session_id,
                                                            tokens)
            self.connection_manager.record_generation_complete(session_id)
            duration = time.monotonic() - start
            log.log_generation(session_id, tokens, duration,
                               ttft_ms=stats.get("ttft_ms"))
            journey_summary = None
            if jr is not None and jr.frames:
                journey_summary = jr.summary()
                # One summary span per request: trace_report.py
                # --journey reads the per-hop frame arrays off it.
                self._tracer.add_span(request_id, "token_journey",
                                      start, time.monotonic(),
                                      **jr.span_attrs())
                get_perf().note_journey(journey_summary["hops_ms"],
                                        jr.frames)
            await self._send(session_id, ws, {
                "type": "response_complete",
                "stats": {
                    # Always numeric, like tokens_per_second below: remote
                    # backends may carry None here (no upstream usage
                    # accounting), but reference-protocol clients treat
                    # this field as a number; chunks_generated carries
                    # the honestly-labelled count.
                    "tokens_generated": tokens,
                    **({"chunks_generated": stats["chunks_generated"]}
                       if "chunks_generated" in stats else {}),
                    "processing_time_ms": stats.get(
                        "processing_time_ms", duration * 1000),
                    # `or 0.0`: remote stats carry None when the
                    # upstream gave no usage accounting, but this field
                    # has always been numeric on the reference protocol
                    # (clients format it); chunks_generated carries the
                    # honest count.
                    "tokens_per_second":
                        stats.get("tokens_per_second") or 0.0,
                    "ttft_ms": stats.get("ttft_ms"),
                    "prompt_tokens": stats.get("prompt_tokens"),
                    # Tokens actually prefilled after prefix-cache /
                    # restore reuse; == prompt_tokens when nothing was
                    # reused, None on remote backends.
                    "prefill_tokens": stats.get("prefill_tokens"),
                    "finish_reason": "cancelled" if cancelled
                    else finish_reason,
                    "provider": self.config.llm_provider,
                    **({"journey": journey_summary}
                       if journey_summary is not None else {}),
                },
            }, request_id=request_id)
        except asyncio.CancelledError:
            self._backend().cancel(request_id)
            raise
        except CircuitBreakerOpen as e:
            await self._send(session_id, ws,
                             {"type": "error", "error": e.to_dict()})
            self.connection_manager.record_error(session_id)
        except AdmissionRejected as e:
            # Load shed at admission (queue bound / overload / drain):
            # the client must back off — to_dict() carries retry_after.
            # Deliberately NOT a breaker failure: shedding is the
            # engine protecting itself, and one overload burst opening
            # the shared breaker would turn load shedding into a full
            # outage.
            self.connection_manager.record_error(session_id)
            await self._send(session_id, ws,
                             {"type": "error", "error": e.to_dict()})
        except LLMServiceError as e:
            # Client-shape rejections raised at the engine seam
            # (category VALIDATION — e.g. an uncompilable structured
            # schema, a too-long prompt) must not open the SHARED
            # breaker: one misbehaving client would 503 every
            # session. Mirrors the /v1 routes' exemption.
            if e.category != ErrorCategory.VALIDATION:
                self.breaker.record_failure()
            self.error_handler.handle_error(e, {"session_id": session_id})
            self.connection_manager.record_error(session_id)
            await self._send(session_id, ws,
                             {"type": "error", "error": e.to_dict()})
        except Exception as e:
            self.breaker.record_failure()
            log.error(f"[{session_id}] generation error: {e}", exc_info=True)
            self.connection_manager.record_error(session_id)
            err = self.error_handler.handle_error(e, {"session_id": session_id})
            await self._send(session_id, ws,
                             {"type": "error", "error": err.to_dict()})
        finally:
            self._cur_request.pop(session_id, None)
            self.connection_manager.update_connection_state(
                session_id, ConnectionState.ACTIVE)

    async def _handle_cancel(self, session_id: str,
                             ws: web.WebSocketResponse) -> None:
        rid = self._cur_request.get(session_id)
        ok = self._backend().cancel(rid) if rid else False
        await self._send(session_id, ws, {"type": "cancelled", "success": ok})

    async def _handle_end_session(self, session_id: str,
                                  ws: web.WebSocketResponse) -> None:
        # Stop any in-flight generation BEFORE tearing the session down,
        # so no token frames trail the session_ended message and the
        # conversation can't be resurrected by a late add_assistant_message.
        task = self._gen_tasks.pop(session_id, None)
        if task is not None and not task.done():
            rid = self._cur_request.get(session_id)
            if rid:
                self._backend().cancel(rid)
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        # Transition BEFORE snapshotting: the stats frame is the
        # protocol's record of the session's final state, and a snapshot
        # taken first reported "active" inside session_ended (VERDICT r4).
        self.connection_manager.update_connection_state(
            session_id, ConnectionState.DISCONNECTING)
        info = self.connection_manager.get_connection(session_id)
        self._backend().release_session(session_id)
        self.conversation_manager.end_session(session_id)
        await self._send(session_id, ws, {
            "type": "session_ended",
            "stats": info.to_dict() if info else {},
        })

    async def _handle_update_config(self, session_id: str, message: dict,
                                    ws: web.WebSocketResponse) -> None:
        cfg = message.get("config", {}) or {}
        updates = self._gen_overrides(cfg)
        if "system_prompt" in cfg:
            updates["system_prompt"] = cfg["system_prompt"]
        self.conversation_manager.update_config(session_id, updates)
        info = self.connection_manager.get_connection(session_id)
        if info is not None:
            info.config.update(cfg)
        if self.agent is not None and hasattr(self.agent, "update_config"):
            self.agent.update_config(**updates)
        await self._send(session_id, ws, {
            "type": "config_updated", "success": True, "config": cfg,
        })
