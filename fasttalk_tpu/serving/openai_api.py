"""OpenAI-compatible HTTP API on the main serving port.

The reference reached its engine through this API from the client side
(vllm_handler.py:117-308 spoke /v1/chat/completions as a consumer);
serving it here means OpenAI-SDK clients, the reference's own vLLM
handler, and any PydanticAI-style framework can point at THIS engine —
the vLLM-parity surface of BASELINE config #3.

Implements: POST /v1/chat/completions (stream SSE + non-stream, with
OpenAI tools/tool_choice/tool_calls — the reference launched vLLM with
--enable-auto-tool-choice --tool-call-parser hermes,
docker-compose.vllm.yml:50-51, so PydanticAI could drive the tool loop;
here the hermes parsing is in-tree and the client drives the loop),
GET /v1/models. Authentication mirrors vLLM's "not needed but accepted".
"""

from __future__ import annotations

import contextlib
import json
import time
import uuid
from typing import Any

from aiohttp import web

from typing import Callable

from fasttalk_tpu.agents.hermes import (
    HermesStreamParser,
    format_tool_result,
    inject_tools_section,
    tools_system_prompt,
)
from fasttalk_tpu.engine.engine import EngineBase, GenerationParams
from fasttalk_tpu.engine.remote import _RemoteEngine
from fasttalk_tpu.observability.trace import (bind_request, get_tracer,
                                              mint_trace_id,
                                              parse_traceparent,
                                              propagate_enabled)
from fasttalk_tpu.structured.compiler import validate_structured_spec
from fasttalk_tpu.utils.errors import (ENGINE_SHED_CODES,
                                       AdmissionRejected, CircuitBreaker,
                                       CircuitBreakerOpen, ErrorCategory,
                                       LLMServiceError)
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("serving.openai")


def _now() -> int:
    return int(time.time())


def _content_str(content: Any) -> str:
    """OpenAI message content may be a string or a list of typed parts."""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        return "".join(p.get("text", "") for p in content
                       if isinstance(p, dict) and p.get("type") == "text")
    return "" if content is None else str(content)


class _BadRequest(ValueError):
    """Client-shape error: surfaces as a 400, never a 500/breaker hit."""


def _reject_429(e: AdmissionRejected) -> web.Response:
    """Load shed at admission → HTTP 429 with both the OpenAI-style
    error body and a standard Retry-After header (integer seconds,
    rounded up — a 0 would invite an immediate hot retry)."""
    import math as _math

    retry_s = max(1, int(_math.ceil(e.retry_after or 1.0)))
    return web.json_response(
        {"error": {"message": e.message, "type": "rate_limit_error",
                   "code": e.reason, "retry_after": e.retry_after}},
        status=429, headers={"Retry-After": str(retry_s)})


def _parse_tools(body: dict) -> tuple[list[dict], str | None]:
    """Extract hermes-format tool specs from an OpenAI `tools` array and
    resolve `tool_choice`. Returns (specs, forced_tool_name) — specs empty
    when tools are absent or tool_choice is "none"; forced_tool_name set
    for tool_choice "required" ("" = any tool) or a named function."""
    tools = body.get("tools")
    choice = body.get("tool_choice")
    if tools is not None and not isinstance(tools, list):
        raise _BadRequest("tools must be a list")
    if not tools:
        if choice == "required" or isinstance(choice, dict):
            raise _BadRequest("tool_choice requires a non-empty tools list")
        return [], None
    if choice == "none":
        return [], None
    specs = []
    for t in tools:
        fn = t.get("function", t) if isinstance(t, dict) else None
        if not isinstance(fn, dict) or not fn.get("name"):
            raise _BadRequest("each tool needs a function.name")
        specs.append({
            "name": fn["name"],
            "description": fn.get("description", ""),
            "parameters": fn.get("parameters",
                                 {"type": "object", "properties": {}}),
        })
    forced: str | None = None
    if choice == "required":
        forced = ""
    elif isinstance(choice, dict):
        fn = choice.get("function")
        if not isinstance(fn, dict) or not fn.get("name"):
            raise _BadRequest(
                "tool_choice object must be "
                '{"type": "function", "function": {"name": ...}}')
        forced = fn["name"]
        if forced not in {s["name"] for s in specs}:
            raise _BadRequest(
                f"tool_choice names unknown tool {forced!r}")
    elif choice not in (None, "auto"):
        raise _BadRequest(f"unsupported tool_choice {choice!r}")
    return specs, forced


def _parse_response_format(body: dict) -> dict | None:
    """OpenAI ``response_format`` → the engine's structured spec
    (docs/STRUCTURED.md). Returns None for absent/"text"."""
    rf = body.get("response_format")
    if rf is None:
        return None
    if not isinstance(rf, dict) or "type" not in rf:
        raise _BadRequest('response_format must be an object with a '
                          '"type"')
    t = rf["type"]
    if t == "text":
        return None
    if t == "json_object":
        return {"kind": "json_object"}
    if t == "json_schema":
        js = rf.get("json_schema")
        schema = js.get("schema") if isinstance(js, dict) else None
        if not isinstance(schema, dict):
            raise _BadRequest(
                "response_format.json_schema.schema must be a JSON "
                "Schema object")
        return {"kind": "json_schema", "schema": schema}
    raise _BadRequest(f"unsupported response_format type {t!r} "
                      "(supported: text, json_object, json_schema)")


def _check_structured_combos(body: dict, structured: dict | None,
                             specs: list[dict] | None = None) -> None:
    """Unsupported-combination guard: every rejection is a clean 400
    naming the clash, never a 500 from deep inside the engine."""
    if structured is None:
        return
    n = body.get("n", 1)
    if n not in (None, 1):
        raise _BadRequest(
            f"response_format with n={n!r} is not supported "
            "(constrained decoding serves one choice per request)")
    if specs:
        raise _BadRequest(
            "response_format cannot be combined with tools: the tool-"
            "call markup would violate the JSON contract (use "
            "tool_choice to force a schema-constrained tool call "
            "instead)")
    if body.get("ignore_eos"):
        raise _BadRequest(
            "response_format is incompatible with ignore_eos=true "
            "(the grammar decides where the document ends)")
    if body.get("stop"):
        raise _BadRequest(
            "response_format is incompatible with stop sequences: a "
            "stop string can truncate the document mid-grammar and "
            "break the validity guarantee (the grammar decides where "
            "the document ends)")


def _structured_denied(engine) -> str | None:
    """The engine's structured-availability reason (None = available).
    Duck-typed: remote/fake engines without the attribute pass the
    spec through and decide upstream."""
    return getattr(_unwrap_agent(engine), "structured_reason", None)


def _hermes_messages(messages: list[dict]) -> list[dict]:
    """Rewrite OpenAI tool-protocol messages (assistant `tool_calls`,
    role "tool" results keyed by tool_call_id) into the hermes markup the
    engine's chat templates render natively."""
    id_to_name: dict[str, str] = {}
    out: list[dict] = []
    for m in messages:
        role = m.get("role", "user")
        content = _content_str(m.get("content"))
        if role == "assistant" and m.get("tool_calls"):
            if not isinstance(m["tool_calls"], list):
                raise _BadRequest("tool_calls must be a list")
            parts = [content] if content else []
            for tc in m["tool_calls"]:
                if not isinstance(tc, dict):
                    raise _BadRequest("tool_calls entries must be objects")
                fn = tc.get("function", {})
                if not isinstance(fn, dict):
                    raise _BadRequest("tool_calls function must be an "
                                      "object")
                args = fn.get("arguments", "{}")
                if isinstance(args, str):
                    try:
                        args = json.loads(args) if args else {}
                    except json.JSONDecodeError:
                        args = {"raw": args}
                if tc.get("id"):
                    id_to_name[tc["id"]] = fn.get("name", "")
                parts.append("<tool_call>" + json.dumps(
                    {"name": fn.get("name", ""), "arguments": args})
                    + "</tool_call>")
            out.append({"role": "assistant", "content": "".join(parts)})
        elif role == "tool":
            name = (m.get("name")
                    or id_to_name.get(m.get("tool_call_id", ""), "tool"))
            out.append({"role": "tool",
                        "content": format_tool_result(name, content)})
        else:
            out.append({"role": role, "content": content})
    return out


def _inject_tools_prompt(messages: list[dict], specs: list[dict],
                         forced: str | None) -> list[dict]:
    section = tools_system_prompt(specs)
    if forced == "":
        section += "\nYou MUST call one of the tools now."
    elif forced:
        section += f"\nYou MUST call the tool {forced!r} now."
    return inject_tools_section(messages, section)


def _unwrap_agent(engine):
    """Route around the native agent's tool loop for surfaces where the
    CLIENT (or nobody) drives tools. Explicit isinstance: any other
    wrapper that happens to hold an inner .engine must NOT be
    bypassed."""
    from fasttalk_tpu.agents.voice_agent import VoiceAgent

    return engine.engine if isinstance(engine, VoiceAgent) else engine


@contextlib.contextmanager
def _trace_scope(request: web.Request, completion_id: str,
                 session_id: str):
    """Trace-context scope for one /v1 completion (docs/OBSERVABILITY
    .md "Fleet tracing"). An incoming ``traceparent`` header (the
    router's RemoteReplicaHandle dispatch sends one) joins that trace;
    otherwise this edge is the root and mints a fresh trace id. The
    ``request_complete`` terminal event is emitted ONLY at the root —
    a router-dispatched inner hop must not duplicate the one-terminal
    marker stitch() counts."""
    tracer = get_tracer().scoped("serving")
    parsed = parse_traceparent(request.headers.get("traceparent", "")) \
        if propagate_enabled() else None
    inner_hop = parsed is not None
    tid = parsed if parsed else mint_trace_id()
    tracer.start(completion_id, session_id, trace_id=tid)
    with bind_request(completion_id, trace_id=tid):
        try:
            yield
        finally:
            if not inner_hop:
                tracer.event(completion_id, "request_complete")
            tracer.finish(completion_id)


def _oai_tool_call(call, index: int) -> dict:
    return {
        "index": index,
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": call.name,
                     "arguments": json.dumps(call.arguments)},
    }


def register_openai_routes(app: web.Application,
                           backend: EngineBase | Callable[[], Any],
                           model_name: str | Callable[[], str],
                           defaults: dict[str, Any] | None = None,
                           breaker: CircuitBreaker | None = None) -> None:
    """``backend`` may be a callable returning the current backend (engine
    or agent — both expose the same generate seam), so the OpenAI route
    goes through the same tool-calling/breaker path as the WebSocket
    route instead of bypassing it."""
    defaults = defaults or {}
    get_backend = backend if callable(backend) else (lambda: backend)
    get_name = model_name if callable(model_name) else (lambda: model_name)

    async def models(request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [{
                "id": get_name(),
                "object": "model",
                "created": _now(),
                "owned_by": "fasttalk-tpu",
            }],
        })

    def _params(body: dict) -> GenerationParams:
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        ignore_eos = body.get("ignore_eos", False)
        if not isinstance(ignore_eos, bool):
            raise _BadRequest(
                f"ignore_eos must be a boolean, got {ignore_eos!r}")
        return GenerationParams(
            temperature=float(body.get(
                "temperature", defaults.get("temperature", 0.7))),
            top_p=float(body.get("top_p", defaults.get("top_p", 0.9))),
            top_k=int(body.get("top_k", defaults.get("top_k", 40))),
            max_tokens=int(body.get("max_tokens")
                           or body.get("max_completion_tokens")
                           or defaults.get("max_tokens", 1024)),
            stop=[s for s in stop if isinstance(s, str) and s],
            # OpenAI wire names for presence/frequency; repeat_penalty
            # is the Ollama-compatible extension (vLLM's /v1 accepts
            # repetition_penalty — both spellings map to it).
            presence_penalty=float(body.get(
                "presence_penalty",
                defaults.get("presence_penalty", 0.0))),
            frequency_penalty=float(body.get(
                "frequency_penalty",
                defaults.get("frequency_penalty", 0.0))),
            # Key-presence defaulting (NOT an `or` chain): an explicit
            # invalid 0 must surface as a 400 from GenerationParams
            # validation, not be silently swapped for the default.
            repeat_penalty=float(
                body["repeat_penalty"] if "repeat_penalty" in body
                else body["repetition_penalty"]
                if "repetition_penalty" in body
                else defaults.get("repeat_penalty", 1.0)),
            ignore_eos=ignore_eos,
            # Admission-control extensions (docs/SCHEDULING.md):
            # priority class + queue deadline; validated by
            # GenerationParams (bad values → 400, not 500).
            priority=str(body.get("priority",
                                  defaults.get("priority",
                                               "interactive"))),
            deadline_s=(float(body["deadline_s"])
                        if body.get("deadline_s") is not None else None),
        )

    def _breaker_503() -> web.Response | None:
        if breaker is None:
            return None
        try:
            breaker.check()
            return None
        except CircuitBreakerOpen as e:
            return web.json_response(
                {"error": {"message": e.message,
                           "type": "server_error",
                           "retry_after": e.retry_after}}, status=503)

    async def _stream_events(resp, engine, completion_id, session_id,
                             messages, params, handle_token, finalize,
                             write_finish) -> None:
        """The SSE event loop both completion surfaces share: token
        routing, terminal mapping, the error frame (a failed stream ends
        on the error frame + [DONE] with no normal finish chunk, so SDK
        clients can't mistake it for success), breaker accounting, and
        slot release."""
        try:
            finish_reason = "stop"
            failed = False
            shed = False
            async for event in engine.generate(completion_id, session_id,
                                               messages, params):
                if event["type"] == "token":
                    await handle_token(event["text"])
                elif event["type"] in ("done", "cancelled"):
                    finish_reason = _oai_finish(
                        event.get("finish_reason", "stop"))
                elif event["type"] == "resumed":
                    # Fleet failover resumed on a survivor: surface as
                    # an SSE comment line — spec-compliant clients
                    # ignore it, curl-level debugging sees it.
                    await resp.write(
                        f": resumed on {event.get('replica')}\n\n"
                        .encode())
                elif event["type"] == "error":
                    failed = True
                    err_payload = event.get("error")
                    if event.get("code") in ENGINE_SHED_CODES:
                        # Queue-deadline expiry / block-pool
                        # exhaustion = load shedding: the frame keeps
                        # retry_after and the breaker is untouched (a
                        # shed is not a backend fault).
                        shed = True
                        err_payload = AdmissionRejected \
                            .from_shed_event(event).to_dict()
                    await resp.write(
                        f"data: {json.dumps({'error': err_payload})}\n\n"
                        .encode())
                    break
            if not failed:
                finish_reason = await finalize(finish_reason)
            if breaker is not None:
                if failed and not shed:
                    breaker.record_failure()
                elif not failed:
                    breaker.record_success()
            if not failed:
                await write_finish(finish_reason)
            await resp.write(b"data: [DONE]\n\n")
        except AdmissionRejected as e:
            # Shed at admission: the stream is already committed as
            # SSE, so the rejection rides an error frame (to_dict
            # carries retry_after) + [DONE]. NOT a breaker failure —
            # shedding is self-protection, not a backend fault.
            await resp.write(
                f"data: {json.dumps({'error': e.to_dict()})}\n\n"
                .encode())
            await resp.write(b"data: [DONE]\n\n")
        except LLMServiceError as e:
            if e.category != ErrorCategory.VALIDATION:
                if breaker is not None:
                    breaker.record_failure()
                raise
            # Client-shape rejection raised by the engine seam (e.g.
            # an uncompilable structured schema): headers are already
            # committed, so it rides an error frame + [DONE] — and the
            # breaker stays closed, same as the 400 the non-stream
            # path returns.
            await resp.write(
                f"data: {json.dumps({'error': e.to_dict()})}\n\n"
                .encode())
            await resp.write(b"data: [DONE]\n\n")
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        finally:
            engine.release_session(session_id)

    async def _collect_events(engine, completion_id, session_id, messages,
                              params, on_token):
        """Non-streaming accumulation both surfaces share. Returns
        (stats, finish_reason, error_response_or_None)."""
        stats: dict[str, Any] = {}
        finish_reason = "stop"
        try:
            async for event in engine.generate(completion_id, session_id,
                                               messages, params):
                if event["type"] == "token":
                    on_token(event["text"])
                elif event["type"] in ("done", "cancelled"):
                    stats = event.get("stats", {})
                    finish_reason = _oai_finish(
                        event.get("finish_reason", "stop"))
                elif event["type"] == "error":
                    if event.get("code") in ENGINE_SHED_CODES:
                        # Shed, not a failure: caller maps to 429.
                        raise AdmissionRejected.from_shed_event(event)
                    if breaker is not None:
                        breaker.record_failure()
                    return stats, finish_reason, web.json_response(
                        {"error": {"message": str(event.get("error")),
                                   "type": "server_error"}}, status=500)
            if breaker is not None:
                breaker.record_success()
        except AdmissionRejected:
            raise  # shed, not a backend failure: caller maps to 429
        except LLMServiceError as e:
            if e.category == ErrorCategory.VALIDATION:
                # Client-shape rejection from the engine seam (e.g. an
                # uncompilable structured schema): caller maps to 400;
                # the breaker stays closed.
                raise
            if breaker is not None:
                breaker.record_failure()
            raise
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        finally:
            engine.release_session(session_id)
        return stats, finish_reason, None

    def _usage(stats: dict) -> dict:
        # `or 0`: remote backends report None when the upstream gave no
        # usage accounting (chunks are never passed off as tokens).
        prompt_tokens = int(stats.get("prompt_tokens") or 0)
        completion_tokens = int(stats.get("tokens_generated") or 0)
        return {"prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens}

    async def _sse_response(request: web.Request) -> web.StreamResponse:
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
        })
        await resp.prepare(request)
        return resp

    async def chat_completions(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body",
                           "type": "invalid_request_error"}}, status=400)
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return web.json_response(
                {"error": {"message": "messages must be a non-empty list",
                           "type": "invalid_request_error"}}, status=400)
        try:
            params = _params(body)
            specs, forced = _parse_tools(body)
            # Structured output (docs/STRUCTURED.md): response_format
            # compiles to a token-FSM constraint; unsupported combos
            # 400 here with the clash named.
            params.structured = _parse_response_format(body)
            _check_structured_combos(body, params.structured, specs)
        except (_BadRequest, TypeError, ValueError) as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}}, status=400)
        completion_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = _now()
        session_id = body.get("user") or f"oai-{completion_id}"
        req_model = body.get("model", get_name())
        engine = get_backend()
        if specs:
            # Client-declared tools mean the CLIENT drives the tool loop
            # (PydanticAI-style). If the configured backend is the native
            # agent, unwrap to the bare engine — otherwise the agent's
            # own hermes loop would strip the markup and execute calls
            # against the server-side registry before this route's parser
            # ever saw them. Explicit isinstance: any other wrapper that
            # happens to hold an inner .engine must NOT be bypassed.
            engine = _unwrap_agent(engine)
        # Passthrough (remote OpenAI/Ollama) backends get the messages
        # VERBATIM: rewriting role-"tool" turns into hermes markup would
        # drop tool_call_id, and strict OpenAI-schema upstreams reject
        # multi-turn tool conversations without it (ADVICE r2). Only the
        # in-tree engine needs the hermes form its templates render.
        # Detect on the UNWRAPPED backend: with no tools declared this
        # turn, `engine` may still be the agent wrapping a remote.
        if not isinstance(_unwrap_agent(engine), _RemoteEngine):
            try:
                messages = _hermes_messages(messages)
            except (_BadRequest, TypeError, ValueError) as e:
                return web.json_response(
                    {"error": {"message": str(e),
                               "type": "invalid_request_error"}},
                    status=400)
        if specs:
            messages = _inject_tools_prompt(messages, specs, forced)
        if forced is not None and params.structured is None \
                and not isinstance(_unwrap_agent(engine), _RemoteEngine) \
                and not params.stop \
                and _structured_denied(engine) is None:
            # tool_choice forced a call: constrain the whole completion
            # to hermes tool-call markup whose *arguments* validate
            # against the tool's parameter schema — the call cannot be
            # malformed (docs/STRUCTURED.md). In-tree engine only;
            # remote upstreams bring their own tool enforcement. The
            # constraint is an internal upgrade the client never asked
            # for, so when this engine build cannot serve constraints
            # (mesh/Pallas/STRUCTURED_MODE=off) — or client stop
            # sequences would clash with the grammar — the request
            # falls back to the pre-existing prompt-injection +
            # stream-parser path instead of being rejected.
            if body.get("ignore_eos"):
                # Same clash the response_format path 400s on: the
                # grammar decides where the call ends. Enforced here
                # because the constraint is attached after _params()
                # ran GenerationParams' own validation.
                return web.json_response(
                    {"error": {"message":
                               "a forcing tool_choice is incompatible "
                               "with ignore_eos=true (the tool-call "
                               "grammar decides where the completion "
                               "ends)",
                               "type": "invalid_request_error"}},
                    status=400)
            params.structured = validate_structured_spec({
                "kind": "tool_call",
                "tools": [{"name": s["name"],
                           "parameters": s["parameters"]}
                          for s in specs
                          if forced == "" or s["name"] == forced]})
        if params.structured is not None:
            reason = _structured_denied(engine)
            if reason is not None:
                return web.json_response(
                    {"error": {"message": "structured output "
                               f"unavailable: {reason}",
                               "type": "invalid_request_error"}},
                    status=400)
        parser = HermesStreamParser() if specs else None
        denied = _breaker_503()
        if denied is not None:
            return denied

        if body.get("stream"):
            resp = await _sse_response(request)

            def chunk(delta: dict, finish: str | None = None) -> bytes:
                payload = {
                    "id": completion_id, "object": "chat.completion.chunk",
                    "created": created, "model": req_model,
                    "choices": [{"index": 0, "delta": delta,
                                 "finish_reason": finish}],
                }
                return f"data: {json.dumps(payload)}\n\n".encode()

            await resp.write(chunk({"role": "assistant"}))
            n_calls = 0

            async def handle_token(text: str) -> None:
                nonlocal n_calls
                if parser is None:
                    await resp.write(chunk({"content": text}))
                    return
                text, calls = parser.feed(text)
                if text:
                    await resp.write(chunk({"content": text}))
                for call in calls:
                    if not call.name:
                        continue  # malformed markup: drop
                    await resp.write(chunk({"tool_calls": [
                        _oai_tool_call(call, n_calls)]}))
                    n_calls += 1

            async def finalize(finish_reason: str) -> str:
                if parser is not None:
                    tail = parser.flush()
                    if tail:
                        await resp.write(chunk({"content": tail}))
                    if n_calls:
                        return "tool_calls"
                return finish_reason

            async def write_finish(finish_reason: str) -> None:
                await resp.write(chunk({}, finish=finish_reason))

            with _trace_scope(request, completion_id, session_id):
                await _stream_events(resp, engine, completion_id,
                                     session_id, messages, params,
                                     handle_token, finalize,
                                     write_finish)
            return resp

        # Non-streaming
        text = ""
        tool_calls: list[dict] = []

        def on_token(t: str) -> None:
            nonlocal text
            if parser is None:
                text += t
                return
            piece, calls = parser.feed(t)
            text += piece
            tool_calls.extend(_oai_tool_call(c, len(tool_calls))
                              for c in calls if c.name)

        try:
            with _trace_scope(request, completion_id, session_id):
                stats, finish_reason, err = await _collect_events(
                    engine, completion_id, session_id, messages, params,
                    on_token)
        except AdmissionRejected as e:
            return _reject_429(e)
        except LLMServiceError as e:
            if e.category != ErrorCategory.VALIDATION:
                raise
            return web.json_response(
                {"error": {"message": e.message,
                           "type": "invalid_request_error"}},
                status=400)
        if err is not None:
            return err
        if parser is not None:
            text += parser.flush()
            if tool_calls:
                finish_reason = "tool_calls"
        message: dict[str, Any] = {"role": "assistant",
                                   "content": text or None}
        if tool_calls:
            message["tool_calls"] = tool_calls
        return web.json_response({
            "id": completion_id,
            "object": "chat.completion",
            "created": created,
            "model": req_model,
            "choices": [{
                "index": 0,
                "message": message,
                "finish_reason": finish_reason,
            }],
            "usage": _usage(stats),
        })

    async def completions(request: web.Request) -> web.StreamResponse:
        """Legacy text completions (/v1/completions): raw prompt, no
        chat template, no tools — vLLM served both surfaces and some
        ecosystem tooling still speaks this one."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body",
                           "type": "invalid_request_error"}}, status=400)
        prompt = body.get("prompt")
        if isinstance(prompt, list):
            if len(prompt) != 1 or not isinstance(prompt[0], str):
                return web.json_response(
                    {"error": {"message": "prompt must be a string (or a "
                               "single-element list of strings)",
                               "type": "invalid_request_error"}}, status=400)
            prompt = prompt[0]
        if not isinstance(prompt, str) or not prompt:
            return web.json_response(
                {"error": {"message": "prompt must be a non-empty string",
                           "type": "invalid_request_error"}}, status=400)
        try:
            params = _params(body)
            params.structured = _parse_response_format(body)
            _check_structured_combos(body, params.structured)
        except (_BadRequest, TypeError, ValueError) as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}}, status=400)
        params.raw_prompt = True  # out-of-band: no template, BOS + bytes
        if (body.get("max_tokens") is None
                and body.get("max_completion_tokens") is None):
            # The legacy endpoint's spec default is 16 (vLLM matches);
            # inheriting the chat default (2048) would surprise clients
            # migrating from a vLLM deployment.
            params.max_tokens = 16
        completion_id = f"cmpl-{uuid.uuid4().hex[:24]}"
        created = _now()
        session_id = body.get("user") or f"oai-{completion_id}"
        req_model = body.get("model", get_name())
        # The raw path never goes through an agent's tool loop.
        engine = _unwrap_agent(get_backend())
        messages = [{"role": "user", "content": prompt}]
        if params.structured is not None:
            reason = _structured_denied(engine)
            if reason is not None:
                return web.json_response(
                    {"error": {"message": "structured output "
                               f"unavailable: {reason}",
                               "type": "invalid_request_error"}},
                    status=400)
        denied = _breaker_503()
        if denied is not None:
            return denied

        if body.get("stream"):
            resp = await _sse_response(request)

            def chunk(text: str, finish: str | None = None) -> bytes:
                payload = {
                    "id": completion_id, "object": "text_completion",
                    "created": created, "model": req_model,
                    "choices": [{"index": 0, "text": text,
                                 "finish_reason": finish}],
                }
                return f"data: {json.dumps(payload)}\n\n".encode()

            async def handle_token(text: str) -> None:
                await resp.write(chunk(text))

            async def finalize(finish_reason: str) -> str:
                return finish_reason

            async def write_finish(finish_reason: str) -> None:
                await resp.write(chunk("", finish=finish_reason))

            with _trace_scope(request, completion_id, session_id):
                await _stream_events(resp, engine, completion_id,
                                     session_id, messages, params,
                                     handle_token, finalize,
                                     write_finish)
            return resp

        text = ""

        def on_token(t: str) -> None:
            nonlocal text
            text += t

        try:
            with _trace_scope(request, completion_id, session_id):
                stats, finish_reason, err = await _collect_events(
                    engine, completion_id, session_id, messages, params,
                    on_token)
        except AdmissionRejected as e:
            return _reject_429(e)
        except LLMServiceError as e:
            if e.category != ErrorCategory.VALIDATION:
                raise
            return web.json_response(
                {"error": {"message": e.message,
                           "type": "invalid_request_error"}},
                status=400)
        if err is not None:
            return err
        return web.json_response({
            "id": completion_id,
            "object": "text_completion",
            "created": created,
            "model": req_model,
            "choices": [{"index": 0, "text": text,
                         "finish_reason": finish_reason}],
            "usage": _usage(stats),
        })

    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/completions", completions)


def _oai_finish(reason: str) -> str:
    return {"stop": "stop", "length": "length", "cancelled": "stop",
            "tool_rounds": "stop"}.get(reason, "stop")
