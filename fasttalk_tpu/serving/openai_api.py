"""OpenAI-compatible HTTP API on the main serving port.

The reference reached its engine through this API from the client side
(vllm_handler.py:117-308 spoke /v1/chat/completions as a consumer);
serving it here means OpenAI-SDK clients, the reference's own vLLM
handler, and any PydanticAI-style framework can point at THIS engine —
the vLLM-parity surface of BASELINE config #3.

Implements: POST /v1/chat/completions (stream SSE + non-stream),
GET /v1/models. Authentication mirrors vLLM's "not needed but accepted".
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any

from aiohttp import web

from typing import Callable

from fasttalk_tpu.engine.engine import EngineBase, GenerationParams
from fasttalk_tpu.utils.errors import CircuitBreaker, CircuitBreakerOpen
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("serving.openai")


def _now() -> int:
    return int(time.time())


def register_openai_routes(app: web.Application,
                           backend: EngineBase | Callable[[], Any],
                           model_name: str | Callable[[], str],
                           defaults: dict[str, Any] | None = None,
                           breaker: CircuitBreaker | None = None) -> None:
    """``backend`` may be a callable returning the current backend (engine
    or agent — both expose the same generate seam), so the OpenAI route
    goes through the same tool-calling/breaker path as the WebSocket
    route instead of bypassing it."""
    defaults = defaults or {}
    get_backend = backend if callable(backend) else (lambda: backend)
    get_name = model_name if callable(model_name) else (lambda: model_name)

    async def models(request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [{
                "id": get_name(),
                "object": "model",
                "created": _now(),
                "owned_by": "fasttalk-tpu",
            }],
        })

    def _params(body: dict) -> GenerationParams:
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        return GenerationParams(
            temperature=float(body.get(
                "temperature", defaults.get("temperature", 0.7))),
            top_p=float(body.get("top_p", defaults.get("top_p", 0.9))),
            top_k=int(body.get("top_k", defaults.get("top_k", 40))),
            max_tokens=int(body.get("max_tokens")
                           or body.get("max_completion_tokens")
                           or defaults.get("max_tokens", 1024)),
            stop=[s for s in stop if isinstance(s, str) and s],
        )

    async def chat_completions(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body",
                           "type": "invalid_request_error"}}, status=400)
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return web.json_response(
                {"error": {"message": "messages must be a non-empty list",
                           "type": "invalid_request_error"}}, status=400)
        params = _params(body)
        completion_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = _now()
        session_id = body.get("user") or f"oai-{completion_id}"
        req_model = body.get("model", get_name())
        engine = get_backend()
        if breaker is not None:
            try:
                breaker.check()
            except CircuitBreakerOpen as e:
                return web.json_response(
                    {"error": {"message": e.message,
                               "type": "server_error",
                               "retry_after": e.retry_after}}, status=503)

        if body.get("stream"):
            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            })
            await resp.prepare(request)

            def chunk(delta: dict, finish: str | None = None) -> bytes:
                payload = {
                    "id": completion_id, "object": "chat.completion.chunk",
                    "created": created, "model": req_model,
                    "choices": [{"index": 0, "delta": delta,
                                 "finish_reason": finish}],
                }
                return f"data: {json.dumps(payload)}\n\n".encode()

            try:
                await resp.write(chunk({"role": "assistant"}))
                finish_reason = "stop"
                failed = False
                async for event in engine.generate(completion_id, session_id,
                                                   messages, params):
                    if event["type"] == "token":
                        await resp.write(chunk({"content": event["text"]}))
                    elif event["type"] in ("done", "cancelled"):
                        finish_reason = _oai_finish(
                            event.get("finish_reason", "stop"))
                    elif event["type"] == "error":
                        failed = True
                        await resp.write(
                            f"data: {json.dumps({'error': event.get('error')})}\n\n"
                            .encode())
                        break
                if breaker is not None:
                    (breaker.record_failure if failed
                     else breaker.record_success)()
                await resp.write(chunk({}, finish=finish_reason))
                await resp.write(b"data: [DONE]\n\n")
            except Exception:
                if breaker is not None:
                    breaker.record_failure()
                raise
            finally:
                engine.release_session(session_id)
            return resp

        # Non-streaming
        text = ""
        stats: dict[str, Any] = {}
        finish_reason = "stop"
        try:
            async for event in engine.generate(completion_id, session_id,
                                               messages, params):
                if event["type"] == "token":
                    text += event["text"]
                elif event["type"] in ("done", "cancelled"):
                    stats = event.get("stats", {})
                    finish_reason = _oai_finish(
                        event.get("finish_reason", "stop"))
                elif event["type"] == "error":
                    if breaker is not None:
                        breaker.record_failure()
                    return web.json_response(
                        {"error": {"message": str(event.get("error")),
                                   "type": "server_error"}}, status=500)
            if breaker is not None:
                breaker.record_success()
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        finally:
            engine.release_session(session_id)
        prompt_tokens = int(stats.get("prompt_tokens", 0))
        completion_tokens = int(stats.get("tokens_generated", 0))
        return web.json_response({
            "id": completion_id,
            "object": "chat.completion",
            "created": created,
            "model": req_model,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish_reason,
            }],
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens,
            },
        })

    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/chat/completions", chat_completions)


def _oai_finish(reason: str) -> str:
    return {"stop": "stop", "length": "length", "cancelled": "stop",
            "tool_rounds": "stop"}.get(reason, "stop")
