"""OpenAI-compatible HTTP API on the main serving port.

The reference reached its engine through this API from the client side
(vllm_handler.py:117-308 spoke /v1/chat/completions as a consumer);
serving it here means OpenAI-SDK clients, the reference's own vLLM
handler, and any PydanticAI-style framework can point at THIS engine —
the vLLM-parity surface of BASELINE config #3.

Implements: POST /v1/chat/completions (stream SSE + non-stream, with
OpenAI tools/tool_choice/tool_calls — the reference launched vLLM with
--enable-auto-tool-choice --tool-call-parser hermes,
docker-compose.vllm.yml:50-51, so PydanticAI could drive the tool loop;
here the hermes parsing is in-tree and the client drives the loop),
GET /v1/models. Authentication mirrors vLLM's "not needed but accepted".
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any

from aiohttp import web

from typing import Callable

from fasttalk_tpu.agents.hermes import (
    HermesStreamParser,
    format_tool_result,
    inject_tools_section,
    tools_system_prompt,
)
from fasttalk_tpu.engine.engine import EngineBase, GenerationParams
from fasttalk_tpu.utils.errors import CircuitBreaker, CircuitBreakerOpen
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("serving.openai")


def _now() -> int:
    return int(time.time())


def _content_str(content: Any) -> str:
    """OpenAI message content may be a string or a list of typed parts."""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        return "".join(p.get("text", "") for p in content
                       if isinstance(p, dict) and p.get("type") == "text")
    return "" if content is None else str(content)


class _BadRequest(ValueError):
    """Client-shape error: surfaces as a 400, never a 500/breaker hit."""


def _parse_tools(body: dict) -> tuple[list[dict], str | None]:
    """Extract hermes-format tool specs from an OpenAI `tools` array and
    resolve `tool_choice`. Returns (specs, forced_tool_name) — specs empty
    when tools are absent or tool_choice is "none"; forced_tool_name set
    for tool_choice "required" ("" = any tool) or a named function."""
    tools = body.get("tools")
    choice = body.get("tool_choice")
    if tools is not None and not isinstance(tools, list):
        raise _BadRequest("tools must be a list")
    if not tools:
        if choice == "required" or isinstance(choice, dict):
            raise _BadRequest("tool_choice requires a non-empty tools list")
        return [], None
    if choice == "none":
        return [], None
    specs = []
    for t in tools:
        fn = t.get("function", t) if isinstance(t, dict) else None
        if not isinstance(fn, dict) or not fn.get("name"):
            raise _BadRequest("each tool needs a function.name")
        specs.append({
            "name": fn["name"],
            "description": fn.get("description", ""),
            "parameters": fn.get("parameters",
                                 {"type": "object", "properties": {}}),
        })
    forced: str | None = None
    if choice == "required":
        forced = ""
    elif isinstance(choice, dict):
        fn = choice.get("function")
        if not isinstance(fn, dict) or not fn.get("name"):
            raise _BadRequest(
                "tool_choice object must be "
                '{"type": "function", "function": {"name": ...}}')
        forced = fn["name"]
        if forced not in {s["name"] for s in specs}:
            raise _BadRequest(
                f"tool_choice names unknown tool {forced!r}")
    elif choice not in (None, "auto"):
        raise _BadRequest(f"unsupported tool_choice {choice!r}")
    return specs, forced


def _hermes_messages(messages: list[dict]) -> list[dict]:
    """Rewrite OpenAI tool-protocol messages (assistant `tool_calls`,
    role "tool" results keyed by tool_call_id) into the hermes markup the
    engine's chat templates render natively."""
    id_to_name: dict[str, str] = {}
    out: list[dict] = []
    for m in messages:
        role = m.get("role", "user")
        content = _content_str(m.get("content"))
        if role == "assistant" and m.get("tool_calls"):
            if not isinstance(m["tool_calls"], list):
                raise _BadRequest("tool_calls must be a list")
            parts = [content] if content else []
            for tc in m["tool_calls"]:
                if not isinstance(tc, dict):
                    raise _BadRequest("tool_calls entries must be objects")
                fn = tc.get("function", {})
                if not isinstance(fn, dict):
                    raise _BadRequest("tool_calls function must be an "
                                      "object")
                args = fn.get("arguments", "{}")
                if isinstance(args, str):
                    try:
                        args = json.loads(args) if args else {}
                    except json.JSONDecodeError:
                        args = {"raw": args}
                if tc.get("id"):
                    id_to_name[tc["id"]] = fn.get("name", "")
                parts.append("<tool_call>" + json.dumps(
                    {"name": fn.get("name", ""), "arguments": args})
                    + "</tool_call>")
            out.append({"role": "assistant", "content": "".join(parts)})
        elif role == "tool":
            name = (m.get("name")
                    or id_to_name.get(m.get("tool_call_id", ""), "tool"))
            out.append({"role": "tool",
                        "content": format_tool_result(name, content)})
        else:
            out.append({"role": role, "content": content})
    return out


def _inject_tools_prompt(messages: list[dict], specs: list[dict],
                         forced: str | None) -> list[dict]:
    section = tools_system_prompt(specs)
    if forced == "":
        section += "\nYou MUST call one of the tools now."
    elif forced:
        section += f"\nYou MUST call the tool {forced!r} now."
    return inject_tools_section(messages, section)


def _oai_tool_call(call, index: int) -> dict:
    return {
        "index": index,
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": call.name,
                     "arguments": json.dumps(call.arguments)},
    }


def register_openai_routes(app: web.Application,
                           backend: EngineBase | Callable[[], Any],
                           model_name: str | Callable[[], str],
                           defaults: dict[str, Any] | None = None,
                           breaker: CircuitBreaker | None = None) -> None:
    """``backend`` may be a callable returning the current backend (engine
    or agent — both expose the same generate seam), so the OpenAI route
    goes through the same tool-calling/breaker path as the WebSocket
    route instead of bypassing it."""
    defaults = defaults or {}
    get_backend = backend if callable(backend) else (lambda: backend)
    get_name = model_name if callable(model_name) else (lambda: model_name)

    async def models(request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [{
                "id": get_name(),
                "object": "model",
                "created": _now(),
                "owned_by": "fasttalk-tpu",
            }],
        })

    def _params(body: dict) -> GenerationParams:
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        return GenerationParams(
            temperature=float(body.get(
                "temperature", defaults.get("temperature", 0.7))),
            top_p=float(body.get("top_p", defaults.get("top_p", 0.9))),
            top_k=int(body.get("top_k", defaults.get("top_k", 40))),
            max_tokens=int(body.get("max_tokens")
                           or body.get("max_completion_tokens")
                           or defaults.get("max_tokens", 1024)),
            stop=[s for s in stop if isinstance(s, str) and s],
        )

    async def chat_completions(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body",
                           "type": "invalid_request_error"}}, status=400)
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return web.json_response(
                {"error": {"message": "messages must be a non-empty list",
                           "type": "invalid_request_error"}}, status=400)
        try:
            params = _params(body)
            specs, forced = _parse_tools(body)
            messages = _hermes_messages(messages)
        except (_BadRequest, TypeError, ValueError) as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}}, status=400)
        if specs:
            messages = _inject_tools_prompt(messages, specs, forced)
        parser = HermesStreamParser() if specs else None
        completion_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = _now()
        session_id = body.get("user") or f"oai-{completion_id}"
        req_model = body.get("model", get_name())
        engine = get_backend()
        if specs:
            # Client-declared tools mean the CLIENT drives the tool loop
            # (PydanticAI-style). If the configured backend is the native
            # agent, unwrap to the bare engine — otherwise the agent's
            # own hermes loop would strip the markup and execute calls
            # against the server-side registry before this route's parser
            # ever saw them. Explicit isinstance: any other wrapper that
            # happens to hold an inner .engine must NOT be bypassed.
            from fasttalk_tpu.agents.voice_agent import VoiceAgent

            if isinstance(engine, VoiceAgent):
                engine = engine.engine
        if breaker is not None:
            try:
                breaker.check()
            except CircuitBreakerOpen as e:
                return web.json_response(
                    {"error": {"message": e.message,
                               "type": "server_error",
                               "retry_after": e.retry_after}}, status=503)

        if body.get("stream"):
            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            })
            await resp.prepare(request)

            def chunk(delta: dict, finish: str | None = None) -> bytes:
                payload = {
                    "id": completion_id, "object": "chat.completion.chunk",
                    "created": created, "model": req_model,
                    "choices": [{"index": 0, "delta": delta,
                                 "finish_reason": finish}],
                }
                return f"data: {json.dumps(payload)}\n\n".encode()

            try:
                await resp.write(chunk({"role": "assistant"}))
                finish_reason = "stop"
                failed = False
                n_calls = 0
                async for event in engine.generate(completion_id, session_id,
                                                   messages, params):
                    if event["type"] == "token":
                        if parser is None:
                            await resp.write(chunk({"content":
                                                    event["text"]}))
                            continue
                        text, calls = parser.feed(event["text"])
                        if text:
                            await resp.write(chunk({"content": text}))
                        for call in calls:
                            if not call.name:
                                continue  # malformed markup: drop
                            await resp.write(chunk({"tool_calls": [
                                _oai_tool_call(call, n_calls)]}))
                            n_calls += 1
                    elif event["type"] in ("done", "cancelled"):
                        finish_reason = _oai_finish(
                            event.get("finish_reason", "stop"))
                    elif event["type"] == "error":
                        failed = True
                        await resp.write(
                            f"data: {json.dumps({'error': event.get('error')})}\n\n"
                            .encode())
                        break
                if parser is not None and not failed:
                    tail = parser.flush()
                    if tail:
                        await resp.write(chunk({"content": tail}))
                    if n_calls:
                        finish_reason = "tool_calls"
                if breaker is not None:
                    (breaker.record_failure if failed
                     else breaker.record_success)()
                if not failed:
                    # A failed stream ends on the error frame + [DONE];
                    # emitting a normal finish chunk would make the turn
                    # look successfully completed to SDK clients.
                    await resp.write(chunk({}, finish=finish_reason))
                await resp.write(b"data: [DONE]\n\n")
            except Exception:
                if breaker is not None:
                    breaker.record_failure()
                raise
            finally:
                engine.release_session(session_id)
            return resp

        # Non-streaming
        text = ""
        tool_calls: list[dict] = []
        stats: dict[str, Any] = {}
        finish_reason = "stop"
        try:
            async for event in engine.generate(completion_id, session_id,
                                               messages, params):
                if event["type"] == "token":
                    if parser is None:
                        text += event["text"]
                        continue
                    t, calls = parser.feed(event["text"])
                    text += t
                    tool_calls.extend(_oai_tool_call(c, len(tool_calls))
                                      for c in calls if c.name)
                elif event["type"] in ("done", "cancelled"):
                    stats = event.get("stats", {})
                    finish_reason = _oai_finish(
                        event.get("finish_reason", "stop"))
                elif event["type"] == "error":
                    if breaker is not None:
                        breaker.record_failure()
                    return web.json_response(
                        {"error": {"message": str(event.get("error")),
                                   "type": "server_error"}}, status=500)
            if breaker is not None:
                breaker.record_success()
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        finally:
            engine.release_session(session_id)
        if parser is not None:
            text += parser.flush()
            if tool_calls:
                finish_reason = "tool_calls"
        message: dict[str, Any] = {"role": "assistant",
                                   "content": text or None}
        if tool_calls:
            message["tool_calls"] = tool_calls
        prompt_tokens = int(stats.get("prompt_tokens", 0))
        completion_tokens = int(stats.get("tokens_generated", 0))
        return web.json_response({
            "id": completion_id,
            "object": "chat.completion",
            "created": created,
            "model": req_model,
            "choices": [{
                "index": 0,
                "message": message,
                "finish_reason": finish_reason,
            }],
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens,
            },
        })

    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/chat/completions", chat_completions)


def _oai_finish(reason: str) -> str:
    return {"stop": "stop", "length": "length", "cancelled": "stop",
            "tool_rounds": "stop"}.get(reason, "stop")
