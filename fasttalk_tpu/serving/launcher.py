"""Service lifecycle: build engine, verify, serve, shut down cleanly.

Parity with the reference launcher (app/core/websocket_launcher.py:41-147:
signal handlers, provider-based server selection, pre-flight backend
verification, uvicorn run, shutdown cleanup) — rebuilt around one asyncio
event loop running both the main app and the monitoring app (the
reference needed a separate Flask thread for monitoring).
"""

from __future__ import annotations

import asyncio
import signal

from aiohttp import web

from fasttalk_tpu.engine.engine import EngineBase
from fasttalk_tpu.engine.factory import build_engine
from fasttalk_tpu.monitoring.monitor import build_monitoring_app
from fasttalk_tpu.serving.server import WebSocketLLMServer
from fasttalk_tpu.utils.config import Config
from fasttalk_tpu.utils.errors import LLMServiceError
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("serving.launcher")


def build_agent(config: Config, engine: EngineBase):
    """Construct the tool-calling agent when enabled (None otherwise)."""
    if not (config.enable_agent and config.enable_tools):
        return None
    try:
        from fasttalk_tpu.agents.voice_agent import VoiceAgent

        return VoiceAgent(engine, config)
    except ImportError:
        return None


def run_spmd_follower(config: Config) -> int:
    """Multi-host SPMD serving, follower role (TPU_SPMD_ROLE=follower):
    build the identical engine over the global mesh and replay the
    leader's device-call stream against this host's shards. No gateway,
    no engine thread — the leader is the cluster's only decision-maker
    (parallel/spmd_serving.py)."""
    from fasttalk_tpu.parallel.spmd_serving import follower_loop

    engine = build_engine(config)
    host, port = config.spmd_addr.rsplit(":", 1)
    log.info(f"SPMD follower: replaying leader calls from "
             f"{host}:{port}")
    follower_loop(engine, host, int(port))
    return 0


class ServerLauncher:
    def __init__(self, config: Config, engine: EngineBase | None = None):
        self.config = config
        self._spmd_sink = None
        if config.spmd_role == "leader" and engine is None:
            # Followers must replay every serving-time device call, so
            # the sink attaches before any traffic — and warmup (which
            # is not published) is forced off for the whole cluster.
            from fasttalk_tpu.parallel.spmd_serving import CallBroadcaster

            if config.warmup not in ("off", "", "none"):
                log.info("SPMD leader: forcing TPU_WARMUP=off "
                         "(warmup calls are not replicated)")
                config.warmup = "off"
            engine = build_engine(config)
            host, port = config.spmd_addr.rsplit(":", 1)
            self._spmd_sink = CallBroadcaster(
                host, int(port), config.spmd_followers)
            engine.call_sink = self._spmd_sink
        if engine is None and config.router_enabled:
            # Router-backed mode (docs/ROUTER.md): the "engine" is a
            # FleetRouter fronting N replicas; the serving stack above
            # is unchanged (the router speaks the engine seam).
            from fasttalk_tpu.router.router import build_fleet

            engine = build_fleet(config)
        self.engine = engine if engine is not None else build_engine(config)
        self.agent = build_agent(config, self.engine)
        self.server = WebSocketLLMServer(config, self.engine, self.agent)
        self._stop = asyncio.Event()
        from fasttalk_tpu.utils.metrics import get_metrics

        self._m_restarts = get_metrics().counter(
            "engine_restarts_total",
            "supervised engine restarts after a crash")

    async def _watchdog(self, interval: float = 5.0) -> None:
        """Supervised in-process recovery: if the engine thread dies,
        rebuild its device state and restart it (the reference's only
        recovery at this layer was docker `restart: unless-stopped`).
        In-flight requests already received terminal error events from
        the crash; new requests are served after the restart."""
        while not self._stop.is_set():
            await asyncio.sleep(interval)
            if self._stop.is_set() or self.engine.check_connection():
                continue
            if self._spmd_sink is not None:
                # In-place restart is leader-local state surgery and is
                # not replicated to followers (engine.restart refuses):
                # an SPMD engine death is fatal to this process so the
                # orchestrator can restart the CLUSTER, instead of the
                # gateway serving errors behind a 5s restart-fail loop.
                log.critical("engine thread died in multi-host SPMD "
                             "mode; shutting the gateway down for a "
                             "cluster restart")
                self._stop.set()
                return
            restart = getattr(self.engine, "restart", None)
            if restart is None or not self.config.engine_auto_restart:
                continue
            log.error("engine thread is down; attempting restart")
            try:
                ok = await asyncio.get_running_loop().run_in_executor(
                    None, restart)
            except Exception as e:
                log.error(f"engine restart raised: {e}", exc_info=True)
                ok = False
            if ok:
                self._m_restarts.inc()
            (log.info if ok else log.error)(
                f"engine restart {'succeeded' if ok else 'failed'}")

    def verify_backend(self) -> None:
        """Pre-flight: refuse to serve if the engine isn't healthy
        (reference: websocket_launcher.py:104-105 hard-exits here)."""
        self.engine.warmup(self.config.warmup)
        self.engine.start()
        if not self.engine.check_connection():
            raise LLMServiceError("Engine failed pre-flight check")
        log.info("engine pre-flight check passed",
                 model=self.engine.get_model_info().get("model"))

    async def run(self) -> None:
        self.verify_backend()

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self._stop.set)
            except NotImplementedError:  # non-unix
                pass

        main_runner = web.AppRunner(self.server.app)
        await main_runner.setup()
        await web.TCPSite(main_runner, self.config.host,
                          self.config.port).start()
        log.info(f"WebSocket server on ws://{self.config.host}:"
                 f"{self.config.port}/ws/llm")

        mon_app = build_monitoring_app(
            ready_check=self.engine.check_connection,
            sched_info=getattr(self.engine, "scheduler_debug", None))
        mon_runner = web.AppRunner(mon_app)
        await mon_runner.setup()
        await web.TCPSite(mon_runner, self.config.monitoring_host,
                          self.config.monitoring_port).start()
        log.info(f"Monitoring on http://{self.config.monitoring_host}:"
                 f"{self.config.monitoring_port}/health")

        watchdog = asyncio.create_task(self._watchdog())
        try:
            await self._stop.wait()
        finally:
            log.info("shutting down")
            watchdog.cancel()
            await main_runner.cleanup()
            await mon_runner.cleanup()
            if self.agent is not None:
                # Release tool resources (search backend HTTP session) —
                # otherwise every shutdown leaks its FDs (ADVICE r2).
                await self.agent.aclose()
            self.engine.shutdown()
            if self._spmd_sink is not None:
                # After engine.shutdown(): the engine thread has
                # stopped publishing, so the stop frame is the stream's
                # clean tail.
                self._spmd_sink.close()

    def start(self) -> None:
        """Blocking entry point (signal-driven shutdown)."""
        asyncio.run(self.run())

    def stop(self) -> None:
        self._stop.set()
