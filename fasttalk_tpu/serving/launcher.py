"""Service lifecycle: build engine, verify, serve, shut down cleanly.

Parity with the reference launcher (app/core/websocket_launcher.py:41-147:
signal handlers, provider-based server selection, pre-flight backend
verification, uvicorn run, shutdown cleanup) — rebuilt around one asyncio
event loop running both the main app and the monitoring app (the
reference needed a separate Flask thread for monitoring).
"""

from __future__ import annotations

import asyncio
import signal

from aiohttp import web

from fasttalk_tpu.engine.engine import EngineBase
from fasttalk_tpu.engine.factory import build_engine
from fasttalk_tpu.monitoring.monitor import build_monitoring_app
from fasttalk_tpu.serving.server import WebSocketLLMServer
from fasttalk_tpu.utils.config import Config
from fasttalk_tpu.utils.errors import LLMServiceError
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("serving.launcher")


def build_agent(config: Config, engine: EngineBase):
    """Construct the tool-calling agent when enabled (None otherwise)."""
    if not (config.enable_agent and config.enable_tools):
        return None
    try:
        from fasttalk_tpu.agents.voice_agent import VoiceAgent

        return VoiceAgent(engine, config)
    except ImportError:
        return None


def run_spmd_follower(config: Config) -> int:
    """Multi-host SPMD serving, follower role (TPU_SPMD_ROLE=follower):
    build the identical engine over the global mesh and replay the
    leader's device-call stream against this host's shards. No gateway,
    no engine thread — the leader is the cluster's only decision-maker
    (parallel/spmd_serving.py)."""
    from fasttalk_tpu.parallel.spmd_serving import follower_loop

    engine = build_engine(config)
    host, port = config.spmd_addr.rsplit(":", 1)
    log.info(f"SPMD follower: replaying leader calls from "
             f"{host}:{port}")
    follower_loop(engine, host, int(port),
                  hb_timeout_s=config.spmd_hb_timeout_s)
    return 0


class RestartBudget:
    """Supervisor restart-storm guard (docs/RESILIENCE.md): a bounded
    number of restarts per rolling window, with exponential backoff
    between attempts. A persistently poisoned device state used to
    crash-loop restart attempts at full CPU forever; now the budget
    exhausts, ``/health`` goes dead, and the supervisor stops
    resurrecting — the orchestrator (or an operator) owns recovery
    from there. Clock injectable for tests."""

    def __init__(self, max_restarts: int = 5, window_s: float = 300.0,
                 backoff_s: float = 2.0, backoff_cap_s: float = 60.0,
                 clock=None):
        import time as _time

        self.max_restarts = max(1, max_restarts)
        self.window_s = window_s
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self._clock = clock if clock is not None else _time.monotonic
        self._attempts: list[float] = []  # timestamps inside the window
        self.exhausted = False

    def _prune(self) -> None:
        cutoff = self._clock() - self.window_s
        self._attempts = [t for t in self._attempts if t >= cutoff]

    def allow(self) -> bool:
        """May another restart be attempted now? Exhaustion LATCHES:
        a supervisor that gave up stays given-up until the process is
        replaced — flapping back to life on window expiry would be the
        same crash loop at a slower cadence."""
        if self.exhausted:
            return False
        self._prune()
        if len(self._attempts) >= self.max_restarts:
            self.exhausted = True
            return False
        return True

    def note_attempt(self) -> float:
        """Record one attempt; returns the backoff delay to wait
        before the NEXT attempt (exponential in the in-window attempt
        count, capped)."""
        self._prune()
        self._attempts.append(self._clock())
        n = len(self._attempts)
        return min(self.backoff_cap_s, self.backoff_s * (2 ** (n - 1)))

    def state(self) -> dict:
        self._prune()
        return {
            "state": "exhausted" if self.exhausted else "ok",
            "restarts_in_window": len(self._attempts),
            "max_restarts": self.max_restarts,
            "window_s": self.window_s,
        }


class ServerLauncher:
    def __init__(self, config: Config, engine: EngineBase | None = None):
        self.config = config
        self._spmd_sink = None
        if config.spmd_role == "leader" and engine is None:
            # Followers must replay every serving-time device call, so
            # the sink attaches before any traffic — and warmup (which
            # is not published) is forced off for the whole cluster.
            from fasttalk_tpu.parallel.spmd_serving import CallBroadcaster

            if config.warmup not in ("off", "", "none"):
                log.info("SPMD leader: forcing TPU_WARMUP=off "
                         "(warmup calls are not replicated)")
                config.warmup = "off"
            engine = build_engine(config)
            host, port = config.spmd_addr.rsplit(":", 1)
            self._spmd_sink = CallBroadcaster(
                host, int(port), config.spmd_followers,
                hb_interval_s=config.spmd_hb_interval_s)
            engine.call_sink = self._spmd_sink
        if engine is None and config.router_enabled:
            # Router-backed mode (docs/ROUTER.md): the "engine" is a
            # FleetRouter fronting N replicas; the serving stack above
            # is unchanged (the router speaks the engine seam).
            from fasttalk_tpu.router.router import build_fleet

            engine = build_fleet(config)
        self.engine = engine if engine is not None else build_engine(config)
        self.agent = build_agent(config, self.engine)
        # Elastic replicas (docs/ROUTER.md, router/elastic.py): grow
        # the fleet on queue depth / SLO burn, shrink it back via
        # client-invisible drain-then-migrate. Only meaningful over a
        # FleetRouter (config validates FLEET_SCALE_MAX x router).
        self.scaler = self._build_scaler()
        self.server = WebSocketLLMServer(config, self.engine, self.agent)
        self._stop = asyncio.Event()
        # Restart-storm guard: bounded budget + exponential backoff;
        # exhaustion marks /health dead and stops resurrecting.
        self.restart_budget = RestartBudget(
            max_restarts=config.supervisor_max_restarts,
            window_s=config.supervisor_window_s,
            backoff_s=config.supervisor_backoff_s)
        from fasttalk_tpu.observability.events import get_events
        from fasttalk_tpu.utils.metrics import get_metrics

        self._events = get_events()
        self._m_restarts = get_metrics().counter(
            "engine_restarts_total",
            "supervised engine restarts after a crash")

    def _build_scaler(self):
        cfg = self.config
        if cfg.fleet_scale_max <= 0 \
                or not hasattr(self.engine, "add_replica"):
            return None
        from fasttalk_tpu.observability.slo import get_slo
        from fasttalk_tpu.router.elastic import ElasticScaler
        from fasttalk_tpu.router.replica import ReplicaHandle

        def build_replica(replica_id: str,
                          role: str = "mixed") -> ReplicaHandle:
            # Role-split fleets (router/disagg.py): the scaler passes
            # the starved tier's role; a new prefill replica gets the
            # same deepened queue build_fleet gives the base tier.
            ecfg = cfg
            if role == "prefill":
                from dataclasses import replace as dc_replace
                ecfg = dc_replace(cfg, sched_queue_bound=4
                                  * cfg.sched_queue_bound)
            return ReplicaHandle(replica_id, build_engine(ecfg),
                                 role=role,
                                 dead_probes=cfg.router_dead_probes)

        return ElasticScaler(
            self.engine, build_replica,
            min_replicas=cfg.fleet_scale_min,
            max_replicas=cfg.fleet_scale_max,
            up_queue_depth=cfg.fleet_scale_up_queue,
            down_idle_s=cfg.fleet_scale_down_idle_s,
            check_interval_s=cfg.fleet_scale_check_s,
            slo_alerts=lambda: get_slo().alert_summary())

    def supervisor_info(self) -> dict:
        """Supervisor state for the monitoring port's /health: while
        "exhausted", the health surface reports dead (the supervisor
        will not resurrect the engine again)."""
        return self.restart_budget.state()

    def _ready(self) -> bool:
        """/health/ready: the engine must be up AND the supervisor
        must not have given up on it."""
        return (not self.restart_budget.exhausted
                and self.engine.check_connection())

    async def _watchdog(self, interval: float = 5.0) -> None:
        """Supervised in-process recovery: if the engine thread dies,
        rebuild its device state and restart it (the reference's only
        recovery at this layer was docker `restart: unless-stopped`).
        In-flight requests already received terminal error events from
        the crash; new requests are served after the restart.

        Restart-storm guard (docs/RESILIENCE.md): attempts are
        metered by ``self.restart_budget`` — exponential backoff
        between attempts, at most SUPERVISOR_MAX_RESTARTS per
        SUPERVISOR_WINDOW_S. On exhaustion the supervisor stops
        resurrecting (a persistently poisoned device state must not
        crash-loop at full CPU) and /health reports dead."""
        while not self._stop.is_set():
            await asyncio.sleep(interval)
            if self._stop.is_set():
                return
            if self._spmd_sink is not None \
                    and self._spmd_sink.dead_reason is not None:
                # Cluster liveness: a dead follower killed the cluster
                # (spmd_serving._fatal). Even if the engine thread is
                # still idling, the gateway must come down for a
                # cluster restart.
                log.critical("SPMD cluster dead "
                             f"({self._spmd_sink.dead_reason}); "
                             "shutting the gateway down")
                self._stop.set()
                return
            if self.engine.check_connection():
                continue
            if self._spmd_sink is not None:
                # In-place restart is leader-local state surgery and is
                # not replicated to followers (engine.restart refuses):
                # an SPMD engine death is fatal to this process so the
                # orchestrator can restart the CLUSTER, instead of the
                # gateway serving errors behind a 5s restart-fail loop.
                log.critical("engine thread died in multi-host SPMD "
                             "mode; shutting the gateway down for a "
                             "cluster restart")
                self._stop.set()
                return
            restart = getattr(self.engine, "restart", None)
            if restart is None or not self.config.engine_auto_restart:
                continue
            if not self.restart_budget.allow():
                if self.restart_budget.exhausted:
                    self._note_exhausted()
                continue
            backoff = self.restart_budget.note_attempt()
            log.error("engine thread is down; attempting restart "
                      f"(next attempt no sooner than {backoff:.1f}s)")
            try:
                ok = await asyncio.get_running_loop().run_in_executor(
                    None, restart)
            except Exception as e:
                log.error(f"engine restart raised: {e}", exc_info=True)
                ok = False
            if ok:
                self._m_restarts.inc()
            (log.info if ok else log.error)(
                f"engine restart {'succeeded' if ok else 'failed'}")
            # Exponential backoff before the NEXT attempt regardless of
            # this one's outcome: the storm the guard exists for is
            # succeed-then-recrash (a poisoned device state restarts
            # cleanly and dies on the next dispatch) — spacing only the
            # failed attempts would burn the whole budget in a few
            # watchdog ticks.
            await asyncio.sleep(backoff)

    _exhausted_logged = False

    def _note_exhausted(self) -> None:
        if self._exhausted_logged:
            return
        self._exhausted_logged = True
        st = self.restart_budget.state()
        log.critical(
            f"supervisor restart budget exhausted "
            f"({st['max_restarts']} restarts in {st['window_s']:.0f}s); "
            "the engine stays down and /health reports dead — "
            "restart the process to recover")
        self._events.emit("supervisor_exhausted", severity="critical",
                          **st)

    def verify_backend(self) -> None:
        """Pre-flight: refuse to serve if the engine isn't healthy
        (reference: websocket_launcher.py:104-105 hard-exits here)."""
        self.engine.warmup(self.config.warmup)
        self.engine.start()
        if not self.engine.check_connection():
            raise LLMServiceError("Engine failed pre-flight check")
        log.info("engine pre-flight check passed",
                 model=self.engine.get_model_info().get("model"))

    async def run(self) -> None:
        self.verify_backend()

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self._stop.set)
            except NotImplementedError:  # non-unix
                pass

        main_runner = web.AppRunner(self.server.app)
        await main_runner.setup()
        await web.TCPSite(main_runner, self.config.host,
                          self.config.port).start()
        log.info(f"WebSocket server on ws://{self.config.host}:"
                 f"{self.config.port}/ws/llm")

        mon_app = build_monitoring_app(
            ready_check=self._ready,
            sched_info=getattr(self.engine, "scheduler_debug", None),
            supervisor_info=self.supervisor_info,
            fault_http=self.config.fault_http_enabled,
            # Router-fronted /traces/{rid} fan-out: requests run on
            # replicas, so the monitoring port's local ring would 404
            # on every fleet request without this.
            trace_lookup=getattr(self.engine, "stitched_trace", None))
        mon_runner = web.AppRunner(mon_app)
        await mon_runner.setup()
        await web.TCPSite(mon_runner, self.config.monitoring_host,
                          self.config.monitoring_port).start()
        log.info(f"Monitoring on http://{self.config.monitoring_host}:"
                 f"{self.config.monitoring_port}/health")

        watchdog = asyncio.create_task(self._watchdog())
        if self.scaler is not None:
            self.scaler.start()
            log.info("elastic scaler on: fleet "
                     f"[{self.config.fleet_scale_min}, "
                     f"{self.config.fleet_scale_max}] replicas")
        try:
            await self._stop.wait()
        finally:
            log.info("shutting down")
            if self.scaler is not None:
                self.scaler.stop()
            watchdog.cancel()
            await main_runner.cleanup()
            await mon_runner.cleanup()
            if self.agent is not None:
                # Release tool resources (search backend HTTP session) —
                # otherwise every shutdown leaks its FDs (ADVICE r2).
                await self.agent.aclose()
            self.engine.shutdown()
            if self._spmd_sink is not None:
                # After engine.shutdown(): the engine thread has
                # stopped publishing, so the stop frame is the stream's
                # clean tail.
                self._spmd_sink.close()

    def start(self) -> None:
        """Blocking entry point (signal-driven shutdown)."""
        asyncio.run(self.run())

    def stop(self) -> None:
        self._stop.set()
