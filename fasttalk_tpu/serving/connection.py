"""WebSocket connection registry: admission, state machine, per-session
and global counters.

Capability parity with the reference connection manager
(app/utils/connection_manager.py:18-366) rebuilt asyncio-native: the
reference used a threading.Lock everywhere (and still managed a
self-deadlock in get_detailed_stats, SURVEY.md §5); here every access
happens on the serving event loop, so the design needs no locks at all.
Token counters live in the process-wide metrics registry — one source of
truth instead of the reference's double counting.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any

from fasttalk_tpu.utils.metrics import get_metrics


class ConnectionState(str, Enum):
    CONNECTING = "connecting"
    ACTIVE = "active"
    PROCESSING = "processing"
    IDLE = "idle"
    DISCONNECTING = "disconnecting"


@dataclass
class ConnectionInfo:
    session_id: str
    websocket: Any
    state: ConnectionState = ConnectionState.CONNECTING
    connected_at: float = field(default_factory=time.time)
    last_activity: float = field(default_factory=time.time)
    messages_received: int = 0
    messages_sent: int = 0
    tokens_generated: int = 0
    generations: int = 0
    errors: int = 0
    config: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "session_id": self.session_id,
            "state": self.state.value,
            "connected_at": self.connected_at,
            "duration_seconds": time.time() - self.connected_at,
            "messages_received": self.messages_received,
            "messages_sent": self.messages_sent,
            "tokens_generated": self.tokens_generated,
            "generations": self.generations,
            "errors": self.errors,
        }


class ConnectionManager:
    def __init__(self, max_connections: int = 50,
                 idle_timeout: float = 3600.0):
        self.max_connections = max_connections
        self.idle_timeout = idle_timeout
        self._connections: dict[str, ConnectionInfo] = {}
        m = get_metrics()
        self._m_total = m.counter("ws_connections_total",
                                  "WebSocket connections accepted")
        self._m_rejected = m.counter("ws_connections_rejected_total",
                                     "connections rejected at admission")
        self._m_active = m.gauge("ws_connections_active",
                                 "currently connected sessions")
        # Process-lifetime frame counters: the per-connection fields
        # above die with the connection, so aggregate receive/send rates
        # were invisible to a scraper.
        self._m_recv = m.counter("ws_messages_received_total",
                                 "WS messages received, all sessions")
        self._m_sent = m.counter("ws_messages_sent_total",
                                 "WS frames sent, all sessions")

    def add_connection(self, session_id: str, websocket: Any,
                       ) -> ConnectionInfo | None:
        """Admit a connection; None if at capacity."""
        if len(self._connections) >= self.max_connections:
            self._m_rejected.inc()
            return None
        info = ConnectionInfo(session_id=session_id, websocket=websocket,
                              state=ConnectionState.ACTIVE)
        self._connections[session_id] = info
        self._m_total.inc()
        self._m_active.set(len(self._connections))
        return info

    def remove_connection(self, session_id: str) -> None:
        self._connections.pop(session_id, None)
        self._m_active.set(len(self._connections))

    def get_connection(self, session_id: str) -> ConnectionInfo | None:
        return self._connections.get(session_id)

    def update_connection_state(self, session_id: str,
                                state: ConnectionState) -> None:
        info = self._connections.get(session_id)
        if info:
            info.state = state
            info.last_activity = time.time()

    def record_message_received(self, session_id: str) -> None:
        self._m_recv.inc()
        info = self._connections.get(session_id)
        if info:
            info.messages_received += 1
            info.last_activity = time.time()

    def record_message_sent(self, session_id: str) -> None:
        self._m_sent.inc()
        info = self._connections.get(session_id)
        if info:
            info.messages_sent += 1

    def record_tokens_generated(self, session_id: str, n: int = 1) -> None:
        info = self._connections.get(session_id)
        if info:
            info.tokens_generated += n

    def record_generation_complete(self, session_id: str) -> None:
        info = self._connections.get(session_id)
        if info:
            info.generations += 1
            info.last_activity = time.time()

    def record_error(self, session_id: str) -> None:
        info = self._connections.get(session_id)
        if info:
            info.errors += 1

    def get_active_count(self) -> int:
        return len(self._connections)

    def retry_after_hint(self) -> float:
        """Suggested reconnect back-off (seconds) when admission is
        refused at the connection limit: short when most sessions are
        idle (likely to churn soon), longer when every connection is
        mid-generation."""
        conns = list(self._connections.values())
        if not conns:
            return 1.0
        busy = sum(1 for c in conns
                   if c.state is ConnectionState.PROCESSING)
        return round(2.0 + 8.0 * busy / len(conns), 1)

    def idle_sessions(self, now: float | None = None) -> list[str]:
        now = now or time.time()
        return [sid for sid, c in self._connections.items()
                if now - c.last_activity > self.idle_timeout
                and c.state is not ConnectionState.PROCESSING]

    def get_statistics(self) -> dict[str, Any]:
        conns = list(self._connections.values())
        return {
            "active_connections": len(conns),
            "max_connections": self.max_connections,
            "total_connections": self._m_total.value,
            "rejected_connections": self._m_rejected.value,
            "states": {s.value: sum(1 for c in conns if c.state is s)
                       for s in ConnectionState},
            "total_messages_received": sum(c.messages_received for c in conns),
            "total_messages_sent": sum(c.messages_sent for c in conns),
            "total_tokens_generated": sum(c.tokens_generated for c in conns),
        }

    def get_detailed_stats(self) -> dict[str, Any]:
        # Unlike the reference (connection_manager.py:341-355, which
        # self-deadlocked here), this is plain single-threaded code.
        return {
            **self.get_statistics(),
            "sessions": [c.to_dict() for c in self._connections.values()],
        }
