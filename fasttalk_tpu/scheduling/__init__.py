"""Admission control and request scheduling (docs/SCHEDULING.md)."""

from fasttalk_tpu.scheduling.scheduler import (
    PRIORITIES,
    STATE_DRAINING,
    STATE_HEALTHY,
    STATE_PRESSURED,
    STATE_SHEDDING,
    QueuedRequest,
    RequestScheduler,
)

__all__ = [
    "PRIORITIES",
    "QueuedRequest",
    "RequestScheduler",
    "STATE_DRAINING",
    "STATE_HEALTHY",
    "STATE_PRESSURED",
    "STATE_SHEDDING",
]
