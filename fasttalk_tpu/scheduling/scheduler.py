"""Admission-controlled request scheduling: the engine's front door.

The seed engine queued submissions in an unbounded FIFO list
(engine.py `_waiting`): under sustained overload, queue depth and tail
latency grew without bound and every client eventually timed out
instead of a few being told to back off. This module is the
JetStream-style serving discipline the ROADMAP north star requires —
the system degrades *predictably*:

- **Bounded queue.** At most ``queue_bound`` requests wait; the
  excess is shed immediately with a computed ``retry_after``
  (AdmissionRejected rides the LLMServiceError taxonomy, so the WS
  error frame and the OpenAI route's 429 + Retry-After both carry it).
- **Priority classes.** "interactive" admits before "bulk",
  configurable per session/request; an aging threshold promotes a
  long-waiting bulk head so sustained interactive load can never
  starve bulk entirely.
- **Per-session fairness.** Within a class, sessions round-robin: a
  session that dumps 50 requests gets one admission per turn, not 50
  in a row, and no session waits forever behind a chatty neighbour.
  Pops are O(1) amortised (deque rotations; never a ``list.pop(i)``
  scan like the seed's skip-busy-sessions loop).
- **Deadlines.** Every queued request carries an absolute deadline
  (per-request ``deadline_s`` or the configured default). Expired
  entries are swept out with a terminal event before they ever touch
  the TPU; a submission whose *estimated* wait already exceeds its
  deadline is shed at the door instead of being queued to die.
- **Overload state machine.** healthy → pressured → shedding
  (published as the ``sched_overload_state`` gauge and through the
  health/stats endpoints) so operators and load balancers see the
  transition before the cliff.
- **Graceful drain.** ``begin_drain()`` keeps serving everything
  already queued or running but rejects new submissions with
  ``retry_after`` — wired into server shutdown so a rolling restart
  finishes its users' sentences.

Thread-safety: submissions arrive from asyncio handlers while the
engine thread pops/expires; one lock serialises all structure access
(the critical sections are a few dict/deque ops).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from fasttalk_tpu.observability.events import get_events
from fasttalk_tpu.utils.errors import AdmissionRejected
from fasttalk_tpu.utils.metrics import get_metrics

PRIORITIES = ("interactive", "bulk")

# Overload states, in escalation order; gauge values for Prometheus.
STATE_HEALTHY = "healthy"
STATE_PRESSURED = "pressured"
STATE_SHEDDING = "shedding"
STATE_DRAINING = "draining"
_STATE_GAUGE = {STATE_HEALTHY: 0, STATE_PRESSURED: 1,
                STATE_SHEDDING: 2, STATE_DRAINING: 3}


@dataclass
class QueuedRequest:
    """One queued submission. ``payload`` is opaque to the scheduler
    (the engine stores its _Request there)."""

    request_id: str
    session_id: str
    priority: str
    submitted_at: float          # time.monotonic()
    deadline: float              # absolute monotonic expiry
    payload: Any = None
    cancelled: bool = field(default=False, compare=False)

    def deadline_in_s(self, now: float | None = None) -> float:
        return self.deadline - (time.monotonic() if now is None else now)


class RequestScheduler:
    """Bounded, deadline-aware, session-fair admission queue."""

    def __init__(self, *, queue_bound: int = 256,
                 default_deadline_s: float = 30.0,
                 bulk_aging_s: float = 5.0,
                 slots: int = 16,
                 shed_hold_s: float = 5.0,
                 pressured_frac: float = 0.5,
                 sweep_interval_s: float = 0.05,
                 slo_gate=None,
                 clock=time.monotonic):
        if queue_bound <= 0:
            raise ValueError("queue_bound must be > 0")
        if default_deadline_s <= 0:
            raise ValueError("default_deadline_s must be > 0")
        if bulk_aging_s <= 0:
            raise ValueError("bulk_aging_s must be > 0")
        self.queue_bound = queue_bound
        self.default_deadline_s = default_deadline_s
        self.bulk_aging_s = bulk_aging_s
        self.slots = max(1, slots)
        self.shed_hold_s = shed_hold_s
        self.pressured_frac = pressured_frac
        self._sweep_interval = sweep_interval_s
        # Injectable time source (must be monotonic): every deadline,
        # aging and state decision reads THIS clock, so tests drive
        # expiry-vs-admission races deterministically by warping it
        # (the fake-clock pattern of slo.py/watchdog.py).
        self._clock = clock
        # Optional SLO consult (observability/slo.py should_shed):
        # callable(priority) -> True when this class must be shed
        # because a latency objective is burning. Evaluated OUTSIDE
        # severity checks the queue itself makes — the SLO engine sees
        # what the queue cannot (latency of requests already served).
        self._slo_gate = slo_gate
        self._events = get_events()
        self._lock = threading.Lock()
        # Per class: round-robin deque of session ids + per-session
        # FIFO deques. A session id may linger in the RR after its
        # deque empties (cancel tombstones); pop() drops it lazily.
        self._sessions: dict[str, dict[str, deque[QueuedRequest]]] = {
            p: {} for p in PRIORITIES}
        self._rr: dict[str, deque[str]] = {p: deque() for p in PRIORITIES}
        self._by_id: dict[str, QueuedRequest] = {}
        self._depth = 0               # live (non-tombstone) entries
        self._draining = False
        self._expired_pending: list[QueuedRequest] = []
        self._last_sweep = 0.0
        self._last_shed = float("-inf")
        # EMA of admission→finish service time, fed by the engine at
        # request finish; drives the wait estimate and retry_after.
        self._service_ema_s = 0.0
        m = get_metrics()
        self._m_shed = m.counter(
            "sched_shed_total",
            "submissions shed at admission (queue full, estimated wait "
            "past deadline, or draining)")
        self._m_expired = m.counter(
            "sched_expired_total",
            "queued requests expired past their deadline before "
            "admission")
        self._m_state = m.gauge(
            "sched_overload_state",
            "scheduler overload state (0=healthy 1=pressured "
            "2=shedding 3=draining)")
        self._m_bound = m.gauge("sched_queue_bound",
                                "configured admission queue bound")
        self._m_depth = m.gauge("sched_queue_depth",
                                "live queued requests awaiting admission")
        self._m_bound.set(queue_bound)
        self._m_state.set(0)

    # ---------------- submission side (any thread) ----------------

    def __len__(self) -> int:
        return self._depth

    @property
    def draining(self) -> bool:
        return self._draining

    def submit(self, request_id: str, session_id: str, *,
               priority: str = "interactive",
               deadline_s: float | None = None,
               payload: Any = None,
               wait_discount_s: float = 0.0) -> QueuedRequest:
        """Enqueue a request, or raise AdmissionRejected (with a
        computed retry_after) when it must be shed: drain mode, queue
        at bound, or estimated wait already past the deadline.

        ``wait_discount_s``: expected service-time saving the caller
        knows about and the queue cannot (the engine passes the
        estimated prefill a parked host-KV restore will skip,
        kvcache/policy.py restore_saving_s) — subtracted from the
        estimated wait before the wait_too_long shed decision, so a
        cheap-to-serve returning session is not turned away by an
        estimate calibrated on full prefills."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        now = self._clock()
        ttl = self.default_deadline_s if deadline_s is None else deadline_s
        # SLO consult BEFORE taking the queue lock: the gate may
        # evaluate burn windows under its own lock, and nesting it
        # inside ours would order the two locks both ways round.
        slo_shed = self._slo_gate is not None \
            and self._slo_gate(priority)
        try:
            with self._lock:
                if self._draining:
                    raise self._shed_locked(
                        now, "server is draining: finishing in-flight "
                        "requests, not accepting new ones",
                        reason="draining")
                if slo_shed:
                    raise self._shed_locked(
                        now, f"{priority} submissions are being shed: "
                        "the service is burning its interactive "
                        "latency SLO budget", reason="slo_burn")
                if self._depth >= self.queue_bound:
                    raise self._shed_locked(
                        now, f"admission queue full "
                        f"({self.queue_bound} waiting)",
                        reason="queue_full")
                est = max(0.0, self._estimate_wait_locked()
                          - max(0.0, wait_discount_s))
                if est > ttl:
                    raise self._shed_locked(
                        now, f"estimated queue wait {est:.1f}s exceeds "
                        f"the request deadline {ttl:.1f}s",
                        reason="wait_too_long")
                entry = QueuedRequest(
                    request_id=request_id, session_id=session_id,
                    priority=priority, submitted_at=now,
                    deadline=now + ttl, payload=payload)
                self._push_locked(entry, front=False)
                self._update_state_locked(now)
                return entry
        except AdmissionRejected as e:
            # One event per shed BURST per reason (coalesced), emitted
            # outside the queue lock — see _shed_locked.
            payload_ev = getattr(e, "_shed_event", None)
            if payload_ev:
                self._events.emit("shed_burst", severity="warning",
                                  coalesce_s=5.0,
                                  coalesce_key=payload_ev["reason"],
                                  **payload_ev)
            raise

    def _shed_locked(self, now: float, message: str,
                     reason: str) -> AdmissionRejected:
        if reason == "queue_full":
            # Only capacity sheds drive the overload state machine: a
            # wait_too_long shed can be caused entirely by ONE client's
            # unrealistically small deadline_s, and flipping /health to
            # "shedding" for it would let a single misbehaving client
            # distort the operator/load-balancer signal.
            self._last_shed = now
        self._m_shed.inc()
        retry = self._retry_after_locked()
        self._update_state_locked(now)
        exc = AdmissionRejected(message, retry_after=retry, reason=reason)
        # Event payload rides the exception; submit() emits it AFTER
        # releasing the queue lock — emit() may mirror to a (possibly
        # slow) EVENTS_JSONL disk, and that write must never serialise
        # concurrent submitters and the engine's pop against this lock.
        exc._shed_event = {"reason": reason, "depth": self._depth,
                           "bound": self.queue_bound,
                           "retry_after": round(retry, 2)}
        return exc

    def _push_locked(self, entry: QueuedRequest, front: bool) -> None:
        sessions = self._sessions[entry.priority]
        q = sessions.get(entry.session_id)
        if q is None:
            sessions[entry.session_id] = q = deque()
            rr = self._rr[entry.priority]
            # The sid may already sit in the RR as a stale entry (its
            # queue emptied via an expiry sweep, which doesn't touch
            # the RR): re-appending would hand the session two turns
            # per round. Membership scan is bounded by queue_bound.
            if entry.session_id not in rr:
                (rr.appendleft if front else rr.append)(entry.session_id)
        (q.appendleft if front else q.append)(entry)
        self._by_id[entry.request_id] = entry
        self._depth += 1
        self._m_depth.set(self._depth)

    def cancel(self, request_id: str) -> QueuedRequest | None:
        """Remove a queued request (O(1): tombstone + index drop).
        Returns the entry if it was still queued, else None."""
        with self._lock:
            entry = self._by_id.pop(request_id, None)
            if entry is None:
                return None
            entry.cancelled = True
            self._depth -= 1
            self._m_depth.set(self._depth)
            self._update_state_locked(self._clock())
            return entry

    # ---------------- admission side (engine thread) ----------------

    def pop(self, busy_sessions: set[str] | frozenset[str] = frozenset(),
            now: float | None = None) -> QueuedRequest | None:
        """Next admissible request, honouring priority (with bulk
        aging), per-session round-robin, deadlines and tombstones.
        Sessions in ``busy_sessions`` are skipped but stay queued.
        Entries found expired are diverted to take_expired()."""
        now = self._clock() if now is None else now
        with self._lock:
            for priority in self._class_order_locked(now):
                entry = self._pop_class_locked(priority, busy_sessions,
                                               now)
                if entry is not None:
                    self._update_state_locked(now)
                    return entry
            return None

    def _class_order_locked(self, now: float) -> tuple[str, ...]:
        # Aging: when the bulk class's next-in-turn head entry has
        # waited past bulk_aging_s, bulk admits first this pop —
        # sustained interactive load can delay bulk, never starve it.
        rr = self._rr["bulk"]
        sessions = self._sessions["bulk"]
        # Drop stale heads (queues emptied by an expiry sweep) here:
        # under sustained interactive load the bulk class may never be
        # popped, so pop()'s lazy cleanup would never reach them and a
        # stale head would permanently mask the aging check.
        while rr and rr[0] not in sessions:
            rr.popleft()
        if rr:
            q = sessions[rr[0]]
            if q and now - q[0].submitted_at > self.bulk_aging_s:
                return ("bulk", "interactive")
        return ("interactive", "bulk")

    def _pop_class_locked(self, priority: str, busy, now: float,
                          ) -> QueuedRequest | None:
        rr = self._rr[priority]
        sessions = self._sessions[priority]
        for _ in range(len(rr)):
            sid = rr.popleft()
            q = sessions.get(sid)
            entry = None
            while q:
                head = q.popleft()
                if head.cancelled:
                    continue  # tombstone; depth already decremented
                if head.deadline <= now:
                    self._expire_entry_locked(head)
                    continue
                entry = head
                break
            if entry is None:
                sessions.pop(sid, None)  # drained; rr entry dropped
                continue
            if sid in busy:
                # Restore the head and rotate the session to the tail:
                # it stays queued while its earlier turn runs.
                q.appendleft(entry)
                rr.append(sid)
                continue
            if q:
                rr.append(sid)  # fairness: session goes to the back
            else:
                sessions.pop(sid, None)
            self._by_id.pop(entry.request_id, None)
            self._depth -= 1
            self._m_depth.set(self._depth)
            return entry
        return None

    def requeue_front(self, entry: QueuedRequest) -> None:
        """Put a just-popped entry back at the head of its session's
        queue (no free slot this iteration); it keeps its deadline and
        its next-in-turn position."""
        with self._lock:
            self._push_locked(entry, front=True)

    def _expire_entry_locked(self, entry: QueuedRequest) -> None:
        self._by_id.pop(entry.request_id, None)
        self._depth -= 1
        self._m_depth.set(self._depth)
        self._m_expired.inc()
        self._expired_pending.append(entry)

    def take_expired(self, now: float | None = None,
                     ) -> list[QueuedRequest]:
        """Expired entries needing a terminal event. Sweeps the whole
        queue at most every ``sweep_interval_s`` (bounded by
        queue_bound, so the engine loop never pays an unbounded scan)
        and drains entries pop() found expired."""
        now = self._clock() if now is None else now
        with self._lock:
            if now - self._last_sweep >= self._sweep_interval:
                self._last_sweep = now
                for priority in PRIORITIES:
                    sessions = self._sessions[priority]
                    for sid in list(sessions):
                        q = sessions[sid]
                        if not any(e.cancelled or e.deadline <= now
                                   for e in q):
                            continue
                        keep: deque[QueuedRequest] = deque()
                        for e in q:
                            if e.cancelled:
                                continue
                            if e.deadline <= now:
                                self._expire_entry_locked(e)
                            else:
                                keep.append(e)
                        if keep:
                            sessions[sid] = keep
                        else:
                            # rr keeps the sid; pop() drops it lazily.
                            sessions.pop(sid, None)
            out, self._expired_pending = self._expired_pending, []
            if out:
                self._update_state_locked(now)
            return out

    # ---------------- lifecycle ----------------

    def begin_drain(self) -> None:
        """Stop admitting new submissions; queued and in-flight work
        still completes. Irreversible for this scheduler instance."""
        with self._lock:
            already = self._draining
            self._draining = True
            self._update_state_locked(self._clock())
        if not already:
            self._events.emit("drain", depth=self._depth,
                              bound=self.queue_bound)

    def clear(self) -> None:
        """Drop every queued entry (engine shutdown/crash: the caller
        emits the terminal events via its request registry)."""
        with self._lock:
            for p in PRIORITIES:
                self._sessions[p].clear()
                self._rr[p].clear()
            self._by_id.clear()
            self._depth = 0
            self._expired_pending.clear()
            self._m_depth.set(0)
            self._update_state_locked(self._clock())

    def remove_finished(self) -> None:
        """Drop entries whose payload already carries a terminal state
        (restart after a crash: _abort_all errored them; their queue
        entries must not be re-admitted)."""
        with self._lock:
            for p in PRIORITIES:
                sessions = self._sessions[p]
                for sid in list(sessions):
                    keep: deque[QueuedRequest] = deque()
                    for e in sessions[sid]:
                        if e.cancelled:
                            continue  # tombstone: not counted in depth
                        if getattr(e.payload, "finished", False):
                            self._by_id.pop(e.request_id, None)
                            self._depth -= 1
                        else:
                            keep.append(e)
                    if keep:
                        sessions[sid] = keep
                    else:
                        sessions.pop(sid, None)
            self._m_depth.set(self._depth)

    # ---------------- estimation + state ----------------

    def note_service_time(self, seconds: float) -> None:
        """Feed one request's admission→finish wall time into the
        service-time EMA (drives wait estimates and retry_after)."""
        if seconds <= 0:
            return
        with self._lock:
            if self._service_ema_s == 0.0:
                self._service_ema_s = seconds
            else:
                self._service_ema_s = (0.8 * self._service_ema_s
                                       + 0.2 * seconds)

    def _estimate_wait_locked(self) -> float:
        """Expected queue wait for a submission arriving now: queue
        depth over slot-level service rate. Zero until the first
        request finishes (conservative: never shed on no data)."""
        return (self._depth / self.slots) * self._service_ema_s

    def estimate_wait(self) -> float:
        with self._lock:
            return self._estimate_wait_locked()

    def _retry_after_locked(self) -> float:
        base = self._estimate_wait_locked() or self._service_ema_s or 1.0
        return min(30.0, max(1.0, base))

    def retry_after(self) -> float:
        """Suggested client back-off in seconds, bounded to [1, 30]."""
        with self._lock:
            return self._retry_after_locked()

    def overload_state(self, now: float | None = None) -> str:
        now = self._clock() if now is None else now
        with self._lock:
            return self._state_locked(now)

    def _state_locked(self, now: float) -> str:
        if self._draining:
            return STATE_DRAINING
        if self._depth >= self.queue_bound \
                or now - self._last_shed <= self.shed_hold_s:
            return STATE_SHEDDING
        if self._depth >= self.pressured_frac * self.queue_bound:
            return STATE_PRESSURED
        return STATE_HEALTHY

    def _update_state_locked(self, now: float) -> None:
        self._m_state.set(_STATE_GAUGE[self._state_locked(now)])

    # ---------------- read side ----------------

    def stats(self) -> dict[str, Any]:
        now = self._clock()
        with self._lock:
            return {
                "state": self._state_locked(now),
                "depth": self._depth,
                "bound": self.queue_bound,
                "draining": self._draining,
                "shed_total": self._m_shed.value,
                "expired_total": self._m_expired.value,
                "service_time_ema_s": round(self._service_ema_s, 4),
                "estimated_wait_s": round(self._estimate_wait_locked(),
                                          4),
            }

    def snapshot(self, now: float | None = None) -> list[dict[str, Any]]:
        """Queued entries in approximate admission order, with position
        and remaining deadline — /debug/requests."""
        now = self._clock() if now is None else now
        out: list[dict[str, Any]] = []
        with self._lock:
            pos = 0
            for priority in self._class_order_locked(now):
                rr = self._rr[priority]
                sessions = self._sessions[priority]
                # Walk sessions in RR order, one entry per turn, like
                # pop() would — positions reflect real admission order.
                cursors = {sid: 0 for sid in rr if sid in sessions}
                order = [sid for sid in rr if sid in sessions]
                progressed = True
                while progressed:
                    progressed = False
                    for sid in order:
                        q = sessions[sid]
                        i = cursors[sid]
                        while i < len(q) and q[i].cancelled:
                            i += 1
                        if i >= len(q):
                            cursors[sid] = i
                            continue
                        e = q[i]
                        cursors[sid] = i + 1
                        progressed = True
                        out.append({
                            "request_id": e.request_id,
                            "session_id": e.session_id,
                            "priority": e.priority,
                            "position": pos,
                            "queued_s": round(now - e.submitted_at, 3),
                            "deadline_in_s": round(e.deadline - now, 3),
                        })
                        pos += 1
        return out
