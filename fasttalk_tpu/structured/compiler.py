"""Structured-spec normalisation + the (schema, tokenizer) FSM cache.

A constrained request carries a *spec* (validated at the serving edge,
GenerationParams.validate_structured_spec): one of

    {"kind": "json_object"}
    {"kind": "json_schema", "schema": {...}}
    {"kind": "regex",       "regex": "..."}
    {"kind": "tool_call",   "tools": [{"name", "parameters"}, ...]}

Compilation (schema → regex → byte DFA → token FSM) is pure host work
— milliseconds for chat-scale schemas on a small vocab, whole seconds
for a large schema over a 100k vocab — so it runs on a dedicated
single worker thread (``compile_fsm_async``), never on the engine
thread or the event loop: admission is never blocked by a cold schema.
Results are LRU-cached per (canonical spec, tokenizer identity); a hot
schema costs one dict lookup. In-flight compiles of the same key are
deduplicated (a burst of identical response_format requests compiles
once).

Observability: ``fsm_compile_ms`` histogram (cache misses only),
``structured_fsm_cache_{hits,misses}_total`` counters.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any

from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.structured.fsm import (TokenFSM, lift_dfa,
                                         token_byte_table)
from fasttalk_tpu.structured.regex_dfa import RegexError, compile_regex
from fasttalk_tpu.structured.schema import (SchemaError,
                                            json_object_regex,
                                            schema_to_regex,
                                            tool_call_regex)
from fasttalk_tpu.utils.metrics import get_metrics

STRUCTURED_KINDS = ("json_object", "json_schema", "regex", "tool_call")


class StructuredError(ValueError):
    """Bad or uncompilable structured spec — a client-shape error
    (400 / invalid_config), never a 500."""


def validate_structured_spec(spec: Any) -> dict:
    """Shape-check a client-supplied spec; returns it normalised.
    Raises StructuredError naming the bad field."""
    if not isinstance(spec, dict):
        raise StructuredError(
            f"structured must be an object with a 'kind', got "
            f"{type(spec).__name__}")
    kind = spec.get("kind")
    if kind not in STRUCTURED_KINDS:
        raise StructuredError(
            f"structured.kind must be one of {STRUCTURED_KINDS}, "
            f"got {kind!r}")
    if kind == "json_schema" and not isinstance(spec.get("schema"), dict):
        raise StructuredError(
            "structured.schema must be a JSON Schema object")
    if kind == "regex" and not (isinstance(spec.get("regex"), str)
                                and spec["regex"]):
        raise StructuredError(
            "structured.regex must be a non-empty pattern string")
    if kind == "tool_call" and not (isinstance(spec.get("tools"), list)
                                    and spec["tools"]):
        raise StructuredError(
            "structured.tools must be a non-empty tool-spec list")
    return spec


def spec_key(spec: dict, json_depth: int) -> str:
    """Cache key: kind + payload with KEY ORDER PRESERVED — object
    property declaration order is part of the compiled contract (the
    document emits properties in that order), so two schemas differing
    only in property order must NOT alias to one FSM. Wrapper-level
    key-order differences merely cost a cache miss."""
    return json.dumps({**spec, "_depth": json_depth},
                      separators=(",", ":"), default=str)


def spec_to_regex(spec: dict, json_depth: int) -> str:
    kind = spec["kind"]
    try:
        if kind == "json_object":
            return json_object_regex(json_depth)
        if kind == "json_schema":
            return schema_to_regex(spec["schema"])
        if kind == "regex":
            return spec["regex"]
        return tool_call_regex(spec["tools"])
    except (SchemaError, RegexError) as e:
        raise StructuredError(f"structured spec does not compile: {e}") \
            from e


class FSMCompiler:
    """LRU of compiled TokenFSMs for ONE tokenizer (engine-owned: the
    tokenizer's vocab is baked into every compiled table)."""

    def __init__(self, tokenizer: Any, *, cache_size: int = 64,
                 max_states: int = 4096, json_depth: int = 3,
                 sample_vocab: int | None = None):
        self._tokenizer = tokenizer
        self._cache_size = max(1, cache_size)
        self.max_states = max_states
        self.json_depth = json_depth
        self.sample_vocab = (sample_vocab if sample_vocab is not None
                             else int(getattr(tokenizer, "vocab_size",
                                              0)))
        self._lock = threading.Lock()
        self._cache: OrderedDict[str, TokenFSM] = OrderedDict()
        self._inflight: dict[str, Future] = {}
        self._token_bytes: list[bytes | None] | None = None  # lazy
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="fsm-compile")
        m = get_metrics()
        self._m_ms = m.histogram(
            "fsm_compile_ms",
            "schema->regex->DFA->token-FSM compile wall time "
            "(cache misses only)",
            buckets=(1, 4, 16, 64, 256, 1000, 4000, 16000))
        self._m_hit = m.counter("structured_fsm_cache_hits_total",
                                "FSM compile cache hits")
        self._m_miss = m.counter("structured_fsm_cache_misses_total",
                                 "FSM compile cache misses")

    def _tbl(self) -> list[bytes | None]:
        # Built once per engine (vocab scan); guarded by _lock callers.
        if self._token_bytes is None:
            self._token_bytes = token_byte_table(self._tokenizer)
        return self._token_bytes

    def compile(self, spec: dict) -> TokenFSM:
        """Synchronous compile-or-cache (the worker thread's body; also
        usable directly from tests/bench)."""
        key = spec_key(spec, self.json_depth)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
                self._m_hit.inc()
                return hit
        self._m_miss.inc()
        if _fp.enabled:
            # Chaos seam (docs/RESILIENCE.md): a compile-worker fault
            # is a client-shape error (StructuredError -> 400 /
            # invalid_config at the engine seam), never a 500 and
            # never a breaker hit.
            _fp.fire("structured.compile", exc=StructuredError)
        t0 = time.monotonic()
        pattern = spec_to_regex(spec, self.json_depth)
        try:
            dfa = compile_regex(pattern,
                                max_states=max(self.max_states * 4,
                                               1 << 14))
        except RegexError as e:
            raise StructuredError(
                f"structured spec does not compile: {e}") from e
        with self._lock:
            tbl = self._tbl()
        eos = sorted(getattr(self._tokenizer, "eos_ids", ()) or ())
        fsm = lift_dfa(dfa, tbl, eos, self.sample_vocab,
                       max_states=self.max_states, pattern=pattern)
        self._m_ms.observe((time.monotonic() - t0) * 1000)
        with self._lock:
            self._cache[key] = fsm
            self._cache.move_to_end(key)
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
        return fsm

    async def compile_async(self, spec: dict) -> TokenFSM:
        """Event-loop-friendly compile: cache hit returns immediately;
        a miss runs on the compile worker with in-flight dedup."""
        key = spec_key(spec, self.json_depth)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
                self._m_hit.inc()
                return hit
            fut = self._inflight.get(key)
            if fut is None:
                fut = self._pool.submit(self.compile, spec)
                self._inflight[key] = fut

                def _clear(_f, key=key):
                    with self._lock:
                        self._inflight.pop(key, None)

                fut.add_done_callback(_clear)
        return await asyncio.wrap_future(fut)

    def stats(self) -> dict:
        with self._lock:
            return {"cached": len(self._cache),
                    "cache_size": self._cache_size,
                    "bytes": sum(f.nbytes for f in self._cache.values())}

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
