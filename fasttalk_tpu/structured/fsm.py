"""Byte DFA → token-level FSM over a real tokenizer vocabulary.

The per-step decode mask needs *token*-level transitions: token ``t``
is allowed in DFA state ``s`` iff walking t's UTF-8 bytes from ``s``
stays inside the (live-pruned) DFA; the token transition is the state
the walk ends in. Lifting walks a byte trie of the whole vocabulary
once per state, so shared token prefixes are walked once (the
Outlines §4 index construction, trie-shared).

Device representation (consumed by structured/runtime.py):

- ``mask_words``  uint32 [n_states, ceil(vocab/32)] — packed allowed
  bitmask, one row gathered per slot per decode step on device.
- token **classes**: tokens with identical transition columns share a
  class, so the next-state table is [n_states, n_classes] instead of
  [n_states, vocab] — for JSON FSMs the class count is tens-to-
  hundreds where the vocab is tens of thousands, which is what makes
  the table small enough to live in HBM next to the KV cache.
- ``next``  int32 [n_states, n_classes], local state ids; ``DEAD`` (-1)
  where disallowed (never gathered for a *sampled* token — the mask
  already excluded it). EOS transitions are implicit: EOS ids sit in
  the dead class, and the host ``step()`` / the arena's table assembly
  turn accept-state EOS into the absorbing ``DONE`` sentinel.

EOS handling is compiled in: accepting states allow the tokenizer's
EOS ids (→ DONE); non-accepting states never do — which is exactly the
"model cannot end the document early" half of the validity guarantee.
Tokens that decode to nothing (specials, padding) are disallowed
everywhere: they would be invisible no-progress loops inside a
constrained generation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from fasttalk_tpu.structured.regex_dfa import DFA

DEAD = -1
DONE = -2

# Forced chains longer than this are cut (jump-forward consumes the
# rest on its next trigger); also the cycle guard for degenerate FSMs.
MAX_FORCED_CHAIN = 512


class FSMTooLarge(ValueError):
    """Compiled FSM exceeds the configured state budget."""


# ---------------------------------------------------- token bytes

def _bytelevel_map() -> dict[str, int]:
    """The GPT-2 byte-level printable-unicode ↔ byte table (the
    ByteLevel pre-tokenizer's encoding; tokenizers/openai encodings
    share it)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def token_byte_table(tokenizer: Any) -> list[bytes | None]:
    """bytes each token id contributes to the output stream; None =
    never allowed in constrained output (specials, empty decodes,
    unmappable ids).

    - ByteTokenizer: ids 0..255 are raw bytes; specials are None.
    - HF fast tokenizers with a ByteLevel pre-tokenizer (the common
      llama/gpt2 family): vocab strings map through the byte-level
      table, so a token holding *half* a UTF-8 character still gets
      its exact bytes (plain decode() would mangle it to U+FFFD).
    - Anything else: per-token decode() fallback; tokens that decode
      to replacement chars are disallowed rather than guessed.
    """
    vocab = int(getattr(tokenizer, "vocab_size", 0))
    rust = getattr(tokenizer, "_tok", None)
    if rust is not None:
        table: list[bytes | None] = [None] * vocab
        bl = _bytelevel_map()
        try:
            specials = {tid for tid in
                        (rust.token_to_id(t.content)
                         for t in rust.get_added_tokens_decoder().values())
                        if tid is not None}
        except Exception:
            specials = set()
        items = rust.get_vocab()  # token string -> id
        mapped = 0
        for tok_str, tid in items.items():
            if tid >= vocab or tid in specials:
                continue
            try:
                table[tid] = bytes(bl[ch] for ch in tok_str)
                mapped += 1
            except KeyError:
                table[tid] = None
        if mapped >= 0.5 * max(1, len(items)):
            return table
        # Not a ByteLevel vocab: decode each id individually.
        table = [None] * vocab
        for tid in range(vocab):
            if tid in specials:
                continue
            text = tokenizer.decode([tid])
            if text and "�" not in text:
                table[tid] = text.encode("utf-8")
        return table
    # Byte-fallback tokenizer (engine/tokenizer.ByteTokenizer shape):
    # ids below 256 are raw bytes, everything above is special.
    table = [None] * vocab
    for tid in range(min(256, vocab)):
        table[tid] = bytes([tid])
    return table


# ---------------------------------------------------- the token FSM

@dataclass
class TokenFSM:
    """One compiled constraint over one tokenizer (immutable; shared
    across requests via the compiler cache)."""

    n_states: int
    start: int
    vocab: int
    n_classes: int
    cls: np.ndarray          # int32 [vocab] — token -> class (0 = dead)
    next: np.ndarray         # int32 [n_states, n_classes]
    mask_words: np.ndarray   # uint32 [n_states, ceil(vocab/32)]
    accept: frozenset[int]
    # Exactly-one-token states: the forced token id, else -1.
    forced_tok: np.ndarray   # int32 [n_states]
    eos_ids: tuple[int, ...]
    pattern: str = ""
    _chains: dict[int, tuple[list[int], int]] = field(
        default_factory=dict, repr=False)

    def step(self, state: int, token_id: int) -> int:
        """Host-side transition (mirrors the device gather)."""
        if state in (DEAD, DONE):
            return state
        if token_id in self.eos_ids:
            return DONE if state in self.accept else DEAD
        if token_id >= self.vocab:
            return DEAD
        return int(self.next[state, self.cls[token_id]])

    def is_terminal(self, state: int) -> bool:
        """Accepting with EOS as the only allowed continuation: the
        document is complete and the engine may finish with
        finish_reason "stop" without spending a step on the EOS."""
        return state in self.accept and int(self.forced_tok[state]) == -2

    def forced_chain(self, state: int) -> tuple[list[int], int]:
        """The maximal single-outgoing-transition chain from ``state``
        (empty when the state allows a choice or is accepting): the
        tokens jump-forward can emit without model steps, and the state
        the chain ends in. Cached per state; chains are capped at
        MAX_FORCED_CHAIN (the follow-up trigger consumes the rest)."""
        hit = self._chains.get(state)
        if hit is not None:
            return hit
        chain: list[int] = []
        cur = state
        while (cur not in (DEAD, DONE) and cur not in self.accept
               and len(chain) < MAX_FORCED_CHAIN):
            tok = int(self.forced_tok[cur])
            if tok < 0:
                break
            chain.append(tok)
            cur = int(self.next[cur, self.cls[tok]])
        out = (chain, cur)
        self._chains[state] = out
        return out

    @property
    def nbytes(self) -> int:
        return (self.cls.nbytes + self.next.nbytes
                + self.mask_words.nbytes + self.forced_tok.nbytes)


def lift_dfa(dfa: DFA, token_bytes: Sequence[bytes | None],
             eos_ids: Sequence[int], vocab: int,
             max_states: int = 4096, pattern: str = "") -> TokenFSM:
    """Lift a byte DFA to a TokenFSM over ``vocab`` token ids.

    ``token_bytes`` may cover fewer ids than ``vocab`` (model vocab
    larger than tokenizer vocab); uncovered ids are disallowed.
    """
    # Byte trie over the vocabulary: node = (children: {byte: node},
    # token ids ending exactly here).
    root: dict = {}
    ends_here: dict[int, list[int]] = {}  # id(trie node) -> token ids
    for tid in range(min(vocab, len(token_bytes))):
        tb = token_bytes[tid]
        if not tb:  # None or empty: invisible in output — disallowed
            continue
        node = root
        for b in tb:
            node = node.setdefault(b, {})
        ends_here.setdefault(id(node), []).append(tid)

    # Per-state token transitions, collected by DFS over (trie, DFA).
    def lift_state(s: int) -> dict[int, int]:
        row: dict[int, int] = {}
        stack = [(root, s)]
        while stack:
            node, ds = stack.pop()
            toks = ends_here.get(id(node))
            if toks is not None:
                for tid in toks:
                    row[tid] = ds
            trans = dfa.transitions[ds]
            for b, child in node.items():
                nxt = trans.get(b)
                if nxt is not None:
                    stack.append((child, nxt))
        return row

    # Only TOKEN-level reachable states matter: a byte-DFA state in the
    # middle of a multi-byte character (or mid-keyword) is walked
    # *through* by a token but never rested in when the vocabulary only
    # spells that region with merged tokens — such states legitimately
    # have no token of their own and must not fail compilation (nor
    # waste mask rows). BFS from start over token transitions.
    lifted_by_old: dict[int, dict[int, int]] = {}
    work = [dfa.start]
    while work:
        s = work.pop()
        if s in lifted_by_old:
            continue
        if len(lifted_by_old) >= max_states:
            # The bound is on TOKEN-FSM states — what the device arena
            # actually holds — not on the (typically much larger) byte
            # DFA (compile_regex carries its own resource guard).
            # Checked mid-BFS so an oversized schema stops lifting
            # immediately instead of finishing the walk first.
            raise FSMTooLarge(
                f"token FSM exceeds {max_states} states "
                f"(STRUCTURED_MAX_STATES); simplify the schema or "
                "raise the knob")
        row = lift_state(s)
        lifted_by_old[s] = row
        work.extend(ds for ds in row.values()
                    if ds not in lifted_by_old)
    order = sorted(lifted_by_old)
    remap = {old: new for new, old in enumerate(order)}
    n = len(order)
    lifted: list[dict[int, int]] = [
        {tid: remap[ds] for tid, ds in lifted_by_old[old].items()}
        for old in order
    ]
    accept_set = frozenset(remap[s] for s in dfa.accept
                           if s in remap)
    start = remap[dfa.start]

    eos = tuple(sorted({e for e in eos_ids if 0 <= e < vocab}))

    # Token classes: group tokens by their full transition column.
    cols: dict[int, list[tuple[int, int]]] = {}
    for s in range(n):
        for tid, ds in lifted[s].items():
            cols.setdefault(tid, []).append((s, ds))
    class_of: dict[tuple, int] = {}
    cls = np.zeros((vocab,), np.int32)  # class 0 = dead everywhere
    class_rows: list[list[tuple[int, int]]] = [[]]
    for tid, col in cols.items():
        key = tuple(col)
        ci = class_of.get(key)
        if ci is None:
            ci = len(class_rows)
            class_of[key] = ci
            class_rows.append(col)
        cls[tid] = ci

    n_classes = len(class_rows)
    nxt = np.full((n, n_classes), DEAD, np.int32)
    for ci, col in enumerate(class_rows):
        if ci == 0:
            continue
        for s, ds in col:
            nxt[s, ci] = ds

    # Packed masks + forced-token detection.
    words = (vocab + 31) // 32
    mask = np.zeros((n, words), np.uint32)
    forced = np.full((n,), -1, np.int32)
    for s in range(n):
        row = lifted[s]
        ids = np.fromiter(row.keys(), np.int64, len(row)) \
            if row else np.empty((0,), np.int64)
        if len(ids):
            np.bitwise_or.at(mask[s], ids // 32,
                             np.uint32(1) << (ids % 32).astype(np.uint32))
        if s in accept_set:
            for e in eos:
                mask[s, e // 32] |= np.uint32(1) << np.uint32(e % 32)
            if not row:
                forced[s] = -2  # terminal: EOS-only continuation
            if not eos and not row:
                # No EOS in vocab and nothing else allowed: the state
                # must still offer one legal bit or on-device sampling
                # degenerates; allow token 0 (host finishes first via
                # is_terminal, so this is belt-and-braces).
                mask[s, 0] |= np.uint32(1)
        elif len(ids) == 1:
            forced[s] = int(ids[0])
        elif not row:
            # A token-REACHABLE non-accepting state with no outgoing
            # token: the vocabulary genuinely cannot spell any
            # continuation of this constraint (e.g. a tokenizer with
            # no way to write '{'). Masking cannot fix that — fail
            # with a client-shape error.
            raise FSMTooLarge(
                f"state {s} has no allowed token: the tokenizer cannot "
                "spell any continuation of this constraint")

    return TokenFSM(n_states=n, start=start, vocab=vocab,
                    n_classes=n_classes, cls=cls, next=nxt,
                    mask_words=mask, accept=accept_set,
                    forced_tok=forced, eos_ids=eos, pattern=pattern)
