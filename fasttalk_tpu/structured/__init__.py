"""Structured decoding: grammar/JSON-schema-constrained generation.

Schema → regex → byte DFA → token FSM, applied as a per-slot packed-
bitmask logit mask inside the jitted decode step, with jump-forward
emission of forced token runs. See docs/STRUCTURED.md.
"""

from fasttalk_tpu.structured.compiler import (FSMCompiler,
                                              StructuredError,
                                              validate_structured_spec)
from fasttalk_tpu.structured.fsm import (DEAD, DONE, FSMTooLarge,
                                         TokenFSM, lift_dfa,
                                         token_byte_table)
from fasttalk_tpu.structured.regex_dfa import (DFA, RegexError,
                                               compile_regex)
from fasttalk_tpu.structured.runtime import (ArenaFull, FSMArena,
                                             DONE_STATE, FREE_SEL,
                                             FREE_STATE, pack_mask_row)
from fasttalk_tpu.structured.schema import (SchemaError,
                                            json_object_regex,
                                            schema_to_regex,
                                            tool_call_regex)

__all__ = [
    "FSMCompiler", "StructuredError", "validate_structured_spec",
    "TokenFSM", "FSMTooLarge", "lift_dfa", "token_byte_table",
    "DEAD", "DONE", "DFA", "RegexError", "compile_regex",
    "ArenaFull", "FSMArena", "DONE_STATE", "FREE_SEL", "FREE_STATE",
    "pack_mask_row", "SchemaError", "json_object_regex",
    "schema_to_regex", "tool_call_regex",
]
