r"""Anchored regex → byte-level DFA, pure Python (interegular-free).

The constrained-decoding compiler (docs/STRUCTURED.md) needs a DFA it
fully owns: states must be enumerable, transitions must be walkable
byte-by-byte (tokens are byte strings, and one UTF-8 character may span
several tokens of a byte-level tokenizer), and dead states must be
prunable so every reachable state is guaranteed a path to acceptance —
the property that makes the per-step logit mask a *guarantee* rather
than a heuristic. ``re`` exposes none of that, so this module compiles
a deliberately small regex dialect itself:

    literals   escapes  \\ \" \n \r \t \f \b \d \w \s and \x{hh}
    classes    [abc] [a-z0-9] [^"\\] (ranges, escapes, negation)
    any        .  (any character except newline)
    groups     ( ... )        alternation  a|b
    repeats    * + ? {m} {m,} {m,n}

The dialect is consumed only by schema.py's generators (the user never
writes raw regex against it except via the ``regex`` structured kind),
so it favours predictability over features: no backrefs, no lookaround,
no lazy quantifiers — everything stays regular and compiles to a DFA.

Unicode: patterns are character-level; compilation lowers characters to
UTF-8 bytes. A class covering "everything except a few ASCII chars"
(the JSON string-body case) lowers its non-ASCII part to the standard
well-formed-UTF-8 byte automaton, so multi-byte characters are accepted
byte-by-byte and a token carrying half a glyph still walks the DFA.
Explicit non-ASCII characters in a class lower to their byte sequences.

Thompson NFA → subset construction → reachable/live pruning. States
that cannot reach an accepting state are removed entirely; a transition
into them simply does not exist, so the token mask can never steer a
generation into a dead end.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class RegexError(ValueError):
    """Pattern outside the supported dialect (message names the spot)."""


# Counted repeats unroll into NFA copies BEFORE any DFA-size guard can
# run; an unbounded client-supplied count ("a{2000000000}") would OOM
# the compile worker at NFA construction. Generous for real schemas
# (strings/arrays longer than this have no business in a logit mask).
MAX_REPEAT = 4096


# ---------------------------------------------------------------- AST

@dataclass
class _Node:
    pass


@dataclass
class _Lit(_Node):          # one character class (set of codepoints or
    chars: object           # ("neg", frozenset) for a negated class
    # chars: frozenset[int] | tuple("neg", frozenset[int])


@dataclass
class _Cat(_Node):
    parts: list


@dataclass
class _Alt(_Node):
    options: list


@dataclass
class _Rep(_Node):
    inner: _Node
    lo: int
    hi: int | None          # None = unbounded


_ESCAPES = {"n": 0x0A, "r": 0x0D, "t": 0x09, "f": 0x0C, "b": 0x08,
            "0": 0x00}
_CLASS_SHORTHAND = {
    "d": frozenset(range(0x30, 0x3A)),
    "w": frozenset(list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
                   + list(range(0x61, 0x7B)) + [0x5F]),
    "s": frozenset([0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B]),
}


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def error(self, msg: str) -> RegexError:
        return RegexError(f"{msg} at position {self.i} in {self.p!r}")

    def peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self) -> _Node:
        node = self.alternation()
        if self.i != len(self.p):
            raise self.error("unbalanced ')'")
        return node

    def alternation(self) -> _Node:
        options = [self.concat()]
        while self.peek() == "|":
            self.take()
            options.append(self.concat())
        return options[0] if len(options) == 1 else _Alt(options)

    def concat(self) -> _Node:
        parts: list[_Node] = []
        while True:
            ch = self.peek()
            if ch is None or ch in "|)":
                break
            parts.append(self.repeat())
        if not parts:
            return _Cat([])  # empty string
        return parts[0] if len(parts) == 1 else _Cat(parts)

    def repeat(self) -> _Node:
        atom = self.atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.take()
                atom = _Rep(atom, 0, None)
            elif ch == "+":
                self.take()
                atom = _Rep(atom, 1, None)
            elif ch == "?":
                self.take()
                atom = _Rep(atom, 0, 1)
            elif ch == "{":
                save = self.i
                self.take()
                digits = ""
                while self.peek() and self.peek().isdigit():
                    digits += self.take()
                if not digits:
                    # Not a counted repeat ("{" literal, e.g. JSON).
                    self.i = save
                    break
                lo = int(digits)
                hi: int | None = lo
                if self.peek() == ",":
                    self.take()
                    digits = ""
                    while self.peek() and self.peek().isdigit():
                        digits += self.take()
                    hi = int(digits) if digits else None
                if self.peek() != "}":
                    raise self.error("malformed {m,n} repeat")
                self.take()
                if hi is not None and hi < lo:
                    raise self.error(f"repeat bounds {{{lo},{hi}}} "
                                     "inverted")
                if max(lo, hi or 0) > MAX_REPEAT:
                    raise self.error(
                        f"repeat bound {max(lo, hi or 0)} exceeds the "
                        f"supported maximum {MAX_REPEAT}")
                atom = _Rep(atom, lo, hi)
            else:
                break
        return atom

    def atom(self) -> _Node:
        ch = self.take()
        if ch == "(":
            inner = self.alternation()
            if self.peek() != ")":
                raise self.error("missing ')'")
            self.take()
            return inner
        if ch == "[":
            return self.char_class()
        if ch == ".":
            # Any character except newline (full unicode).
            return _Lit(("neg", frozenset([0x0A])))
        if ch == "\\":
            esc = self.escape()
            # Class shorthands (\d \w \s) escape to a SET of
            # codepoints; single-char escapes to one codepoint.
            return _Lit(esc if isinstance(esc, frozenset)
                        else frozenset([esc]))
        if ch in "*+?":
            raise self.error(f"dangling quantifier {ch!r}")
        return _Lit(frozenset([ord(ch)]))

    def escape(self) -> int | frozenset:
        if self.i >= len(self.p):
            raise self.error("dangling backslash")
        ch = self.take()
        if ch in _ESCAPES:
            return _ESCAPES[ch]
        if ch in _CLASS_SHORTHAND:
            # Returned as a set; callers that need a single char reject.
            return _CLASS_SHORTHAND[ch]
        if ch == "x":
            if self.i + 1 >= len(self.p):
                raise self.error(r"\x needs two hex digits")
            hexpair = self.take() + self.take()
            try:
                return int(hexpair, 16)
            except ValueError:
                raise self.error(rf"bad \x escape {hexpair!r}") from None
        if ch == "u":
            if self.i + 3 >= len(self.p):
                raise self.error(r"\u needs four hex digits")
            quad = "".join(self.take() for _ in range(4))
            try:
                return int(quad, 16)
            except ValueError:
                raise self.error(rf"bad \u escape {quad!r}") from None
        # Everything else escapes to itself ( \{ \} \[ \" \\ \. ... ).
        return ord(ch)

    def char_class(self) -> _Node:
        negated = False
        if self.peek() == "^":
            self.take()
            negated = True
        chars: set[int] = set()
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise self.error("unterminated character class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            self.take()
            if ch == "\\":
                esc = self.escape()
                if isinstance(esc, frozenset):
                    chars |= esc
                    continue
                lo = esc
            else:
                lo = ord(ch)
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.take()  # '-'
                ch2 = self.take()
                if ch2 == "\\":
                    esc2 = self.escape()
                    if isinstance(esc2, frozenset):
                        raise self.error("class shorthand in range")
                    hi = esc2
                else:
                    hi = ord(ch2)
                if hi < lo:
                    raise self.error(f"inverted range "
                                     f"{chr(lo)}-{chr(hi)}")
                chars |= set(range(lo, hi + 1))
            else:
                chars.add(lo)
        fs = frozenset(chars)
        return _Lit(("neg", fs) if negated else fs)


# ------------------------------------------------- character → bytes

def _utf8_tail(nfa: "_NFA", src: int, dst: int, n: int) -> None:
    """n continuation bytes (0x80-0xBF) from src to dst."""
    cur = src
    for k in range(n):
        nxt = dst if k == n - 1 else nfa.new_state()
        nfa.edge(cur, nxt, range(0x80, 0xC0))
        cur = nxt


def _any_non_ascii(nfa: "_NFA", src: int, dst: int) -> None:
    """The well-formed-UTF-8 automaton for any codepoint >= 0x80
    (RFC 3629 byte ranges, surrogate-range excluded)."""
    # 2-byte: C2-DF 80-BF
    s = nfa.new_state()
    nfa.edge(src, s, range(0xC2, 0xE0))
    _utf8_tail(nfa, s, dst, 1)
    # 3-byte: E0 A0-BF 80-BF
    s = nfa.new_state()
    nfa.edge(src, s, [0xE0])
    t = nfa.new_state()
    nfa.edge(s, t, range(0xA0, 0xC0))
    _utf8_tail(nfa, t, dst, 1)
    # 3-byte: E1-EC / EE-EF 80-BF 80-BF
    s = nfa.new_state()
    nfa.edge(src, s, list(range(0xE1, 0xED)) + [0xEE, 0xEF])
    _utf8_tail(nfa, s, dst, 2)
    # 3-byte: ED 80-9F 80-BF (surrogates D800-DFFF excluded)
    s = nfa.new_state()
    nfa.edge(src, s, [0xED])
    t = nfa.new_state()
    nfa.edge(s, t, range(0x80, 0xA0))
    _utf8_tail(nfa, t, dst, 1)
    # 4-byte: F0 90-BF ..., F1-F3 80-BF ..., F4 80-8F ...
    s = nfa.new_state()
    nfa.edge(src, s, [0xF0])
    t = nfa.new_state()
    nfa.edge(s, t, range(0x90, 0xC0))
    _utf8_tail(nfa, t, dst, 2)
    s = nfa.new_state()
    nfa.edge(src, s, range(0xF1, 0xF4))
    _utf8_tail(nfa, s, dst, 3)
    s = nfa.new_state()
    nfa.edge(src, s, [0xF4])
    t = nfa.new_state()
    nfa.edge(s, t, range(0x80, 0x90))
    _utf8_tail(nfa, t, dst, 2)


# An explicit non-ASCII class larger than this must use the negated
# form instead (enumerating each char's byte sequence would explode).
_MAX_EXPLICIT_NON_ASCII = 4096


# ---------------------------------------------------------------- NFA

class _NFA:
    """Thompson NFA over the byte alphabet. Edges carry byte iterables;
    epsilon edges are kept separately."""

    def __init__(self) -> None:
        self.edges: list[dict[int, set[int]]] = []  # state -> byte -> dsts
        self.eps: list[set[int]] = []

    def new_state(self) -> int:
        self.edges.append({})
        self.eps.append(set())
        return len(self.edges) - 1

    def edge(self, src: int, dst: int, bytes_: object) -> None:
        d = self.edges[src]
        for b in bytes_:
            d.setdefault(b, set()).add(dst)

    def epsilon(self, src: int, dst: int) -> None:
        self.eps[src].add(dst)

    # -- fragment builders: each returns nothing, wiring src → dst.

    def lit(self, src: int, dst: int, chars: object) -> None:
        if isinstance(chars, tuple) and chars[0] == "neg":
            excluded = chars[1]
            ascii_ok = [c for c in range(0x80) if c not in excluded]
            self.edge(src, dst, ascii_ok)
            non_ascii_excl = {c for c in excluded if c >= 0x80}
            if not non_ascii_excl:
                _any_non_ascii(self, src, dst)
            else:
                raise RegexError(
                    "negated class excluding non-ASCII characters is "
                    "not supported (JSON never needs it)")
            return
        ascii_chars = [c for c in chars if c < 0x80]
        if ascii_chars:
            self.edge(src, dst, ascii_chars)
        non_ascii = [c for c in chars if c >= 0x80]
        if len(non_ascii) > _MAX_EXPLICIT_NON_ASCII:
            raise RegexError(
                f"character class with {len(non_ascii)} explicit "
                "non-ASCII characters; use a negated class instead")
        for c in non_ascii:
            seq = chr(c).encode("utf-8")
            cur = src
            for k, b in enumerate(seq):
                nxt = dst if k == len(seq) - 1 else self.new_state()
                self.edge(cur, nxt, [b])
                cur = nxt

    def build(self, node: _Node, src: int, dst: int) -> None:
        if isinstance(node, _Lit):
            self.lit(src, dst, node.chars)
        elif isinstance(node, _Cat):
            cur = src
            for i, part in enumerate(node.parts):
                nxt = dst if i == len(node.parts) - 1 else self.new_state()
                self.build(part, cur, nxt)
                cur = nxt
            if not node.parts:
                self.epsilon(src, dst)
        elif isinstance(node, _Alt):
            for opt in node.options:
                self.build(opt, src, dst)
        elif isinstance(node, _Rep):
            cur = src
            for _ in range(node.lo):  # mandatory copies
                nxt = self.new_state()
                self.build(node.inner, cur, nxt)
                cur = nxt
            if node.hi is None:
                # cur -ε-> dst with a loop state for inner*
                loop = self.new_state()
                self.epsilon(cur, loop)
                self.build(node.inner, loop, loop)
                self.epsilon(loop, dst)
            else:
                self.epsilon(cur, dst)
                for _ in range(node.hi - node.lo):  # optional copies
                    nxt = self.new_state()
                    self.build(node.inner, cur, nxt)
                    self.epsilon(nxt, dst)
                    cur = nxt
        else:  # pragma: no cover
            raise RegexError(f"unknown node {node!r}")


# ---------------------------------------------------------------- DFA

@dataclass
class DFA:
    """Byte-level DFA: ``transitions[s]`` maps byte → state; ``accept``
    is the accepting-state set; every state is reachable AND live (can
    reach an accepting state)."""

    transitions: list[dict[int, int]] = field(default_factory=list)
    accept: frozenset[int] = frozenset()
    start: int = 0

    @property
    def n_states(self) -> int:
        return len(self.transitions)

    def walk(self, state: int, data: bytes) -> int | None:
        """Walk ``data`` from ``state``; None on a missing edge."""
        for b in data:
            nxt = self.transitions[state].get(b)
            if nxt is None:
                return None
            state = nxt
        return state

    def matches(self, data: bytes) -> bool:
        end = self.walk(self.start, data)
        return end is not None and end in self.accept


def compile_regex(pattern: str, max_states: int = 1 << 16) -> DFA:
    """Parse + compile one anchored pattern to a pruned byte DFA.

    ``max_states`` bounds the subset construction — a pathological
    pattern fails with a named error instead of eating the host.
    """
    ast = _Parser(pattern).parse()
    nfa = _NFA()
    s0, s1 = nfa.new_state(), nfa.new_state()
    nfa.build(ast, s0, s1)

    def closure(states) -> frozenset[int]:
        out: set[int] = set()
        stack = list(states)
        while stack:
            s = stack.pop()
            if s in out:
                continue
            out.add(s)
            stack.extend(nfa.eps[s])
        return frozenset(out)

    start = closure([s0])
    index: dict[frozenset[int], int] = {start: 0}
    trans: list[dict[int, int]] = [{}]
    accept: set[int] = set()
    if s1 in start:
        accept.add(0)
    work = [start]
    while work:
        cur = work.pop()
        ci = index[cur]
        # byte → union of NFA destinations
        by_byte: dict[int, set[int]] = {}
        for s in cur:
            for b, dsts in nfa.edges[s].items():
                by_byte.setdefault(b, set()).update(dsts)
        for b, dsts in by_byte.items():
            nxt = closure(dsts)
            ni = index.get(nxt)
            if ni is None:
                ni = len(trans)
                if ni >= max_states:
                    raise RegexError(
                        f"DFA exceeds {max_states} states for "
                        f"pattern of length {len(pattern)}")
                index[nxt] = ni
                trans.append({})
                if s1 in nxt:
                    accept.add(ni)
                work.append(nxt)
            trans[ci][b] = ni

    return _prune(DFA(trans, frozenset(accept), 0))


def _prune(dfa: DFA) -> DFA:
    """Keep only states that are reachable from start AND can reach an
    accepting state. This is what upgrades the token mask from "locally
    legal byte" to "a completion to a valid document always exists"."""
    n = dfa.n_states
    # Live: reverse reachability from accepting states.
    rev: list[set[int]] = [set() for _ in range(n)]
    for s, edges in enumerate(dfa.transitions):
        for dst in edges.values():
            rev[dst].add(s)
    live: set[int] = set()
    stack = list(dfa.accept)
    while stack:
        s = stack.pop()
        if s in live:
            continue
        live.add(s)
        stack.extend(rev[s])
    if dfa.start not in live:
        raise RegexError("pattern matches nothing")
    # Reachable within live states.
    keep: set[int] = set()
    stack = [dfa.start]
    while stack:
        s = stack.pop()
        if s in keep:
            continue
        keep.add(s)
        stack.extend(d for d in dfa.transitions[s].values() if d in live)
    remap = {old: new for new, old in enumerate(sorted(keep))}
    trans = [
        {b: remap[d] for b, d in dfa.transitions[old].items() if d in keep}
        for old in sorted(keep)
    ]
    return DFA(trans, frozenset(remap[s] for s in dfa.accept if s in keep),
               remap[dfa.start])
