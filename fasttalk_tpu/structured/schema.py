"""JSON Schema → regex over the in-tree dialect (regex_dfa.py).

The Outlines lowering (Willard & Louf 2023): a schema compiles to one
anchored regex whose language is a subset of the schema's valid
documents; the regex then compiles to a byte DFA and a token FSM. The
subset is deliberate — regular languages cannot carry full JSON Schema
— and every narrowing is explicit:

- **Compact form.** No optional whitespace: one canonical rendering
  (``{"a":1,"b":[2,3]}``-style with no spaces). SGLang's compressed-FSM
  observation applies directly: fixed punctuation becomes single-path
  FSM chains that jump-forward can emit without model steps.
- **Objects.** Properties appear in declaration order and are all
  required (a "required" list naming a subset is rejected with the
  property names, not silently widened). additionalProperties are not
  generated.
- **Recursion.** ``$ref`` into ``$defs``/``definitions`` is inlined;
  a reference cycle is rejected (a recursive schema is not regular).
- **Strings.** JSON string syntax with the standard escapes; full
  unicode bodies (the DFA walks UTF-8 byte-wise). ``enum``/``const``
  compile to exact alternatives; ``pattern`` is rejected by name
  (user-supplied patterns are outside the supported dialect's
  guarantees).
- **json_object mode.** "Any JSON" is not regular either; the generic
  grammar unrolls to ``max_depth`` nesting levels (STRUCTURED_JSON_
  DEPTH), scalars-only at the innermost level.
"""

from __future__ import annotations

import json
from typing import Any


class SchemaError(ValueError):
    """Unsupported or malformed schema; the message names the spot."""


# Regex-dialect metacharacters that must be escaped in literals.
_META = set("\\.[](){}*+?|^$\"")


def _esc(text: str) -> str:
    out = []
    for ch in text:
        if ch in _META:
            out.append("\\" + ch)
        elif ord(ch) < 0x20:
            out.append(f"\\x{ord(ch):02x}")
        else:
            out.append(ch)
    return "".join(out)


# JSON scalar building blocks (compact form).
_INT = r"-?(0|[1-9][0-9]*)"
_NUMBER = _INT + r"(\.[0-9]+)?([eE][+-]?[0-9]+)?"
_BOOL = r"(true|false)"
_NULL = r"null"
# String body: any char except '"', '\' and control chars, or an
# escape sequence. Matches the JSON grammar (compact, no surrogate
# validation beyond UTF-8 well-formedness).
_CHAR = (r'([^"\\\x00-\x1f]'
         r'|\\["\\/bfnrt]'
         r'|\\u[0-9a-fA-F][0-9a-fA-F][0-9a-fA-F][0-9a-fA-F])')
_STRING = r"\"" + _CHAR + r"*\""


def _literal(value: Any) -> str:
    """A regex matching exactly this JSON value (compact encoding)."""
    return _esc(json.dumps(value, ensure_ascii=False,
                           separators=(",", ":")))


def _bound(value, name: str):
    """Length/count bounds compile to counted repeats, which unroll
    into NFA states — cap them before the regex layer must."""
    from fasttalk_tpu.structured.regex_dfa import MAX_REPEAT

    if isinstance(value, int) and value > MAX_REPEAT:
        raise SchemaError(f"{name}={value} exceeds the supported "
                          f"maximum {MAX_REPEAT}")
    return value


def _string_regex(schema: dict) -> str:
    if "pattern" in schema:
        raise SchemaError(
            "string 'pattern' is not supported (user regex is outside "
            "the compiled dialect's guarantees); use enum/const or "
            "min/maxLength")
    lo = _bound(schema.get("minLength", 0), "minLength")
    hi = _bound(schema.get("maxLength"), "maxLength")
    if not isinstance(lo, int) or lo < 0:
        raise SchemaError(f"minLength must be a non-negative integer, "
                          f"got {lo!r}")
    if hi is not None and (not isinstance(hi, int) or hi < lo):
        raise SchemaError(f"maxLength must be an integer >= minLength, "
                          f"got {hi!r}")
    if lo == 0 and hi is None:
        return _STRING
    bound = f"{{{lo},{hi}}}" if hi is not None else f"{{{lo},}}"
    return r"\"" + _CHAR + bound + r"\""


def _number_regex(schema: dict, integer: bool) -> str:
    for k in ("minimum", "maximum", "exclusiveMinimum",
              "exclusiveMaximum", "multipleOf"):
        if k in schema:
            raise SchemaError(
                f"numeric bound {k!r} is not supported (not regular); "
                "use an enum of allowed values")
    return _INT if integer else _NUMBER


def _array_regex(schema: dict, defs: dict, stack: tuple) -> str:
    items = schema.get("items", True)
    item = (_value_regex(defs, stack) if items is True
            else _compile_node(items, defs, stack))
    lo = _bound(schema.get("minItems", 0), "minItems")
    hi = _bound(schema.get("maxItems"), "maxItems")
    if not isinstance(lo, int) or lo < 0:
        raise SchemaError(f"minItems must be a non-negative integer, "
                          f"got {lo!r}")
    if hi is not None and (not isinstance(hi, int) or hi < lo):
        raise SchemaError(f"maxItems must be an integer >= minItems, "
                          f"got {hi!r}")
    if hi == 0:
        return r"\[\]"
    if lo == 0:
        more = r"(," + item + r")*" if hi is None \
            else r"(," + item + r"){0," + str(hi - 1) + r"}"
        return r"\[(" + item + more + r")?\]"
    more = r"(," + item + r")"
    tail = (more + r"{" + str(lo - 1) + r",}" if hi is None
            else more + r"{" + str(lo - 1) + r"," + str(hi - 1) + r"}")
    return r"\[" + item + tail + r"\]"


def _object_regex(schema: dict, defs: dict, stack: tuple) -> str:
    """Object with properties in declaration order. A "required" list
    marks the subset that must appear (optionals may be omitted, order
    preserved); ABSENT "required" means every property is required —
    the predictable fixed shape, matching OpenAI strict mode's
    required-must-name-everything rule rather than draft semantics."""
    props = schema.get("properties")
    if props is None:
        # Free-form object: one nesting level of the generic grammar.
        return _generic_object(_value_regex(defs, stack))
    if not isinstance(props, dict):
        raise SchemaError(f"properties must be an object, got "
                          f"{type(props).__name__}")
    required = schema.get("required")
    if required is None:
        req = set(props)
    else:
        extra = [k for k in required if k not in props]
        if extra:
            raise SchemaError(f"required names undeclared "
                              f"properties: {extra}")
        req = set(required)
    if not props:
        return r"\{\}"
    items = [(_literal(name) + ":" + _compile_node(sub, defs, stack),
              name in req) for name, sub in props.items()]
    # Tail from property i on, each emission comma-prefixed; optional
    # properties wrap in (,p)? — order is fixed, so tails compose by
    # plain concatenation.
    tails = [""] * (len(items) + 1)
    for i in range(len(items) - 1, -1, -1):
        p, is_req = items[i]
        tails[i] = ("," + p + tails[i + 1] if is_req
                    else r"(," + p + r")?" + tails[i + 1])
    # First EMITTED property k carries no comma; every property before
    # it must be optional (and skipped). Empty body iff none required.
    heads = []
    for k, (p, is_req) in enumerate(items):
        heads.append(p + tails[k + 1])
        if is_req:
            break
    else:
        heads.append("")  # all optional: {} is valid
    if len(heads) == 1:
        return r"\{" + heads[0] + r"\}"
    return r"\{(" + "|".join(h if h else "()" for h in heads) + r")\}"


def _generic_object(value: str) -> str:
    member = _STRING + ":" + value
    return r"\{(" + member + r"(," + member + r")*)?\}"


def _generic_array(value: str) -> str:
    return r"\[(" + value + r"(," + value + r")*)?\]"


_SCALAR = "(" + "|".join([_STRING, _NUMBER, _BOOL, _NULL]) + ")"


def json_value_regex(max_depth: int) -> str:
    """Any JSON value, containers unrolled to ``max_depth`` levels
    (scalars only at the innermost)."""
    value = _SCALAR
    for _ in range(max(0, max_depth)):
        value = ("(" + _SCALAR + "|" + _generic_object(value) + "|"
                 + _generic_array(value) + ")")
    return value


def json_object_regex(max_depth: int) -> str:
    """A JSON *object* document (the ``json_object`` response_format
    contract) with values nested to ``max_depth``."""
    return _generic_object(json_value_regex(max(0, max_depth - 1)))


def _value_regex(defs: dict, stack: tuple) -> str:
    # Unconstrained subschema inside a constrained one: modest depth.
    return json_value_regex(2)


def _resolve_ref(ref: str, defs: dict) -> Any:
    for prefix in ("#/$defs/", "#/definitions/"):
        if ref.startswith(prefix):
            name = ref[len(prefix):]
            if name not in defs:
                raise SchemaError(f"unresolvable $ref {ref!r}")
            return name, defs[name]
    raise SchemaError(f"only local $ref into $defs/definitions is "
                      f"supported, got {ref!r}")


def _compile_node(schema: Any, defs: dict, stack: tuple) -> str:
    if schema is True or schema == {}:
        return _value_regex(defs, stack)
    if schema is False:
        raise SchemaError("schema 'false' matches nothing")
    if not isinstance(schema, dict):
        raise SchemaError(f"schema node must be an object, got "
                          f"{type(schema).__name__}")
    if "$ref" in schema:
        name, sub = _resolve_ref(schema["$ref"], defs)
        if name in stack:
            raise SchemaError(
                f"recursive $ref {schema['$ref']!r} (cycle via "
                f"{' -> '.join(stack + (name,))}); recursive schemas "
                "are not regular — bound the depth explicitly")
        return _compile_node(sub, defs, stack + (name,))
    if "const" in schema:
        return _literal(schema["const"])
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise SchemaError(f"enum must be a non-empty list, "
                              f"got {vals!r}")
        return "(" + "|".join(_literal(v) for v in vals) + ")"
    for key in ("anyOf", "oneOf"):
        if key in schema:
            opts = schema[key]
            if not isinstance(opts, list) or not opts:
                raise SchemaError(f"{key} must be a non-empty list")
            return "(" + "|".join(_compile_node(o, defs, stack)
                                  for o in opts) + ")"
    if "allOf" in schema:
        raise SchemaError("allOf is not supported (schema "
                          "intersection is not regular in general)")
    t = schema.get("type")
    if isinstance(t, list):
        return "(" + "|".join(
            _compile_node({**schema, "type": one}, defs, stack)
            for one in t) + ")"
    if t == "string":
        return _string_regex(schema)
    if t == "integer":
        return _number_regex(schema, integer=True)
    if t == "number":
        return _number_regex(schema, integer=False)
    if t == "boolean":
        return _BOOL
    if t == "null":
        return _NULL
    if t == "array":
        return _array_regex(schema, defs, stack)
    if t == "object":
        return _object_regex(schema, defs, stack)
    if t is None:
        # No type, no combinator: any value.
        return _value_regex(defs, stack)
    raise SchemaError(f"unsupported type {t!r}")


def schema_to_regex(schema: dict) -> str:
    """Compile one JSON Schema document to an anchored regex."""
    if not isinstance(schema, dict):
        raise SchemaError(f"schema must be an object, got "
                          f"{type(schema).__name__}")
    defs = {}
    for key in ("$defs", "definitions"):
        sub = schema.get(key)
        if isinstance(sub, dict):
            defs.update(sub)
    return _compile_node(schema, defs, ())


def tool_call_regex(tools: list[dict]) -> str:
    """Hermes tool-call markup with schema-constrained arguments:

        <tool_call>{"name": "N", "arguments": A}</tool_call>

    ``tools`` are hermes specs ({"name", "parameters"}); the arguments
    object of each alternative is compiled from its parameters schema.
    The field spelling matches tools_system_prompt exactly (one space
    after each colon — the format the model was instructed to emit).
    """
    if not tools:
        raise SchemaError("tool_call constraint needs at least one tool")
    alts = []
    for t in tools:
        name = t.get("name")
        if not name:
            raise SchemaError("tool spec without a name")
        params = t.get("parameters") or {"type": "object",
                                         "properties": {}}
        if not isinstance(params, dict):
            raise SchemaError(f"tool {name!r} parameters must be an "
                              "object schema")
        pdefs = {}
        for key in ("$defs", "definitions"):
            sub = params.get(key)
            if isinstance(sub, dict):
                pdefs.update(sub)
        try:
            args = _compile_node(params, pdefs, ())
        except SchemaError:
            # A tool schema outside the compilable subset (pattern,
            # numeric bounds, recursion) must not fail the whole
            # request: tool_choice enforcement degrades to "arguments
            # are a well-formed JSON object" — the markup and JSON
            # guarantees hold, only the per-field validation is
            # relaxed for THIS tool.
            args = _generic_object(json_value_regex(2))
        alts.append(r"\{\"name\": " + _literal(name)
                    + r", \"arguments\": " + args + r"\}")
    return (r"<tool_call>(" + "|".join(alts) + r")</tool_call>")
