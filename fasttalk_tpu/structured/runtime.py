"""Device-resident FSM union arena for the engine's decode loop.

One batched decode step serves up to ``num_slots`` concurrent
generations, each possibly constrained by a *different* FSM. The jitted
step cannot index per-request Python objects, so every active FSM's
tables are packed into ONE set of device arrays with disjoint state
ranges, and each slot carries (state, selector) indices into them:

    masks  uint32 [state_cap, ceil(vocab/32)]   per-STATE allowed bits
    nexts  int32  [state_cap, class_cap]        per-STATE transitions
    cls    int32  [sel_cap,   vocab]            per-FSM token classes

Global state 0 is FREE (every token allowed, self-loop) — the state
every unconstrained slot sits in, so the same jitted program serves
mixed batches with the mask a no-op for free rows. Global state 1 is
DONE (EOS-only, absorbing) — where a completed constrained generation
parks while pipelined calls drain past its finish.

Capacities bucket to powers of two (bounded by the STRUCTURED_STATE_
BUDGET knob), so the jitted decode executables key on a handful of
shapes, not on every schema's exact state count. Registration happens
at admission on the engine thread; the (numpy) arena is rebuilt only
when a new FSM enters, and re-uploaded as one host→device put — never
on the per-step hot path. Released FSMs stay resident (sticky) until
capacity pressure evicts them, so the common serve-many-requests-of-
one-schema pattern uploads once.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from fasttalk_tpu.structured.fsm import DEAD, DONE, TokenFSM

FREE_STATE = 0
DONE_STATE = 1
_RESERVED_STATES = 2
FREE_SEL = 0
_RESERVED_SELS = 1


class ArenaFull(RuntimeError):
    """No room for another FSM while every resident one is pinned."""


@dataclass
class _Entry:
    fsm: TokenFSM
    base: int          # global state offset
    sel: int           # row in the cls table
    refs: int = 0


class FSMArena:
    """Host-side assembly of the union tables (numpy); the engine owns
    the device upload. Engine-thread only (no locking)."""

    def __init__(self, vocab: int, eos_ids: tuple[int, ...],
                 num_slots: int, state_budget: int = 8192):
        self.vocab = vocab
        self.words = (vocab + 31) // 32
        self.eos_ids = tuple(e for e in eos_ids if 0 <= e < vocab)
        self.state_budget = max(state_budget, _RESERVED_STATES + 2)
        self.sel_cap = 1 << (num_slots + _RESERVED_SELS - 1).bit_length()
        self._entries: dict[int, _Entry] = {}   # id(fsm) -> entry
        self._order: list[_Entry] = []          # registration order
        self.state_cap = 0
        self.class_cap = 0
        self.masks: np.ndarray | None = None
        self.cls: np.ndarray | None = None
        self.nexts: np.ndarray | None = None
        self.dirty = False   # device copy stale

    # ------------------------------------------------- registration

    def register(self, fsm: TokenFSM) -> _Entry:
        """Pin one FSM into the arena (idempotent per object). Raises
        ArenaFull when the state budget cannot hold it even after
        evicting every unpinned entry."""
        entry = self._entries.get(id(fsm))
        if entry is not None:
            entry.refs += 1
            return entry
        need = fsm.n_states
        if need + _RESERVED_STATES > self.state_budget:
            raise ArenaFull(
                f"FSM needs {need} states; STRUCTURED_STATE_BUDGET is "
                f"{self.state_budget} (minus {_RESERVED_STATES} "
                "reserved)")
        if self._used_states() + need > self.state_budget \
                or len(self._order) + _RESERVED_SELS >= self.sel_cap:
            self._evict(need)
        entry = _Entry(fsm=fsm, base=0, sel=0, refs=1)
        self._order.append(entry)
        self._entries[id(fsm)] = entry
        self._rebuild()
        return entry

    def release(self, fsm: TokenFSM) -> None:
        entry = self._entries.get(id(fsm))
        if entry is not None and entry.refs > 0:
            entry.refs -= 1
        # Sticky: the tables stay resident for the next request of the
        # same schema; eviction is capacity-driven only.

    def _used_states(self) -> int:
        return _RESERVED_STATES + sum(e.fsm.n_states for e in self._order)

    def _evict(self, need: int) -> None:
        """Drop oldest UNPINNED entries until ``need`` states and one
        selector row fit; raise when the pinned set alone is too big."""
        order = list(self._order)
        states = _RESERVED_STATES + sum(e.fsm.n_states for e in order)

        def fits() -> bool:
            return (states + need <= self.state_budget
                    and len(order) + _RESERVED_SELS < self.sel_cap)

        for e in list(order):
            if fits():
                break
            if e.refs <= 0:
                order.remove(e)
                states -= e.fsm.n_states
                self._entries.pop(id(e.fsm), None)
        self._order = order
        if not fits():
            raise ArenaFull(
                f"{self._used_states()} states pinned by running "
                f"requests; no room for {need} more within "
                f"STRUCTURED_STATE_BUDGET={self.state_budget}")

    # ------------------------------------------------- table build

    def _rebuild(self) -> None:
        """Re-pack every entry into fresh union tables. Offsets are
        reassigned — callers re-derive per-slot global states from the
        entries, which the engine does by patching device state from
        the host mirrors whenever the arena is dirty."""
        total = _RESERVED_STATES
        max_cls = 1
        for e in self._order:
            e.base = total
            total += e.fsm.n_states
            max_cls = max(max_cls, e.fsm.n_classes)
        state_cap = max(4, 1 << (total - 1).bit_length())
        if state_cap > self.state_budget:
            state_cap = total  # over-budget pow2 round-up: exact fit
        class_cap = max(2, 1 << (max_cls - 1).bit_length())

        masks = np.zeros((state_cap, self.words), np.uint32)
        nexts = np.full((state_cap, class_cap), DONE_STATE, np.int32)
        cls = np.zeros((self.sel_cap, self.vocab), np.int32)

        # FREE: everything (< vocab) allowed, absorbing.
        masks[FREE_STATE] = np.uint32(0xFFFFFFFF)
        tail = self.vocab % 32
        if tail:
            masks[FREE_STATE, -1] = np.uint32((1 << tail) - 1)
        nexts[FREE_STATE] = FREE_STATE
        # DONE: EOS-only, absorbing.
        for e in self.eos_ids:
            masks[DONE_STATE, e // 32] |= np.uint32(1) << np.uint32(e % 32)
        if not self.eos_ids:
            masks[DONE_STATE, 0] |= np.uint32(1)
        nexts[DONE_STATE] = DONE_STATE

        for i, e in enumerate(self._order):
            f = e.fsm
            e.sel = _RESERVED_SELS + i
            masks[e.base:e.base + f.n_states] = f.mask_words
            block = f.next.astype(np.int64, copy=True)
            live = block >= 0
            block[live] += e.base
            block[block == DEAD] = DONE_STATE  # unreachable for sampled
            block[block == DONE] = DONE_STATE
            nexts[e.base:e.base + f.n_states, :f.n_classes] = block
            # Padded class columns default to DONE_STATE (harmless:
            # only classes the FSM defines are ever gathered).
            v = min(self.vocab, len(f.cls))
            cls[e.sel, :v] = f.cls[:v]
            # EOS tokens get a dedicated class column so accept-state
            # EOS transitions land in DONE: give them class_cap-1...
            # unless the FSM already classed them (it never does — EOS
            # bytes are specials, disallowed in-body).
        # EOS transition: EOS ids are class 0 ("dead everywhere") in
        # every compiled FSM, and nexts[:, 0] for entry rows is DEAD →
        # DONE_STATE, which is exactly the wanted accept→DONE edge (the
        # mask permits EOS only in accept states, so a sampled EOS can
        # only occur there).
        self.masks, self.nexts, self.cls = masks, nexts, cls
        self.state_cap, self.class_cap = state_cap, class_cap
        self.dirty = True

    # ------------------------------------------------- accessors

    def global_state(self, entry: _Entry, local_state: int) -> int:
        if local_state == DONE:
            return DONE_STATE
        if local_state == DEAD:
            return DONE_STATE
        return entry.base + local_state

    def stats(self) -> dict:
        return {"fsms": len(self._order),
                "pinned": sum(1 for e in self._order if e.refs > 0),
                "states_used": self._used_states(),
                "state_cap": self.state_cap,
                "class_cap": self.class_cap,
                "state_budget": self.state_budget}


def pack_mask_row(fsm: TokenFSM, state: int, words: int,
                  eos_ids: tuple[int, ...]) -> np.ndarray:
    """One packed allowed-row for a host-supplied state (the masked
    first-token sample after prefill / jump-forward), padded to the
    arena's word width."""
    row = np.zeros((words,), np.uint32)
    if state in (DEAD, DONE):
        for e in eos_ids:
            row[e // 32] |= np.uint32(1) << np.uint32(e % 32)
        if not eos_ids:
            row[0] |= np.uint32(1)
        return row
    src = fsm.mask_words[state]
    row[:len(src)] = src
    return row
