"""Int4 group-wise weight quantization tier (WEIGHT_QUANT=int4).

The reference's highest-throughput production config served an AWQ-INT4
checkpoint — quantization it bought from its external vLLM container
(reference: docker-compose.vllm.yml:38-41). This package is the in-tree
answer: group-wise symmetric 4-bit weights with nibble-packed storage
(int4.py), an activation-aware AWQ-style scale search run offline
against the tinychat corpus (awq.py, scripts/quantize_checkpoint.py),
and the serving hot path in ops/quant.py + ops/pallas_int8.py that
dequantizes inside the matmul operand read so the packed bytes are what
crosses HBM. See docs/QUANTIZATION.md.
"""

from fasttalk_tpu.quantization.int4 import (GROUP_DEFAULT, INT4_LEAVES,
                                            dequantize_int4, group_size_of,
                                            is_int4, pack_int4,
                                            quantize_group,
                                            quantize_math_group,
                                            quantize_params_int4,
                                            quantizing_put_int4, unpack_int4,
                                            validate_group)

__all__ = [
    "GROUP_DEFAULT", "INT4_LEAVES", "dequantize_int4", "group_size_of",
    "is_int4", "pack_int4", "quantize_group", "quantize_math_group",
    "quantize_params_int4", "quantizing_put_int4", "unpack_int4",
    "validate_group",
]
