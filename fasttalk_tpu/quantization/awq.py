"""Activation-aware int4 scale search (AWQ-style) for the int4 tier.

Data-free group quantization (int4.py) spends its 16 codes uniformly
over each group's range — but a handful of input channels carry most of
the activation magnitude (the AWQ observation, PAPERS.md), and rounding
THOSE channels coarsely is what actually moves the logits. This module
re-balances the codes with two classic moves, searched per layer
against a real calibration batch:

1. **Norm-fold channel scaling** for the matmuls fed directly by an
   RMSNorm (wq/wk/wv after attn_norm; w_gate/w_up after mlp_norm).
   Per input channel j, ``s_j = mean|h_j| ** alpha`` (geometric-mean
   normalised); the weight rows are multiplied by ``s`` BEFORE
   quantization and the norm's gain vector divided by ``s`` — exact in
   float (``rms_norm(x, n/s) == rms_norm(x, n)/s``), so the only net
   change is where the quantizer spends its precision. Alpha is
   grid-searched per layer-group to minimise output MSE against the
   float matmul on the calibration activations.
2. **Clip search** for the matmuls with no foldable norm upstream
   (wo reads the attention output, w_down the gated MLP product):
   shrinking each group's maxabs by ``c < 1`` clips rare outliers but
   refines the step for everything else; ``c`` is grid-searched per
   layer the same way.

The calibration forward runs the model layer-by-layer in float32 with
the ORIGINAL weights (stats must reflect what the served activations
look like), reusing the exact serving math — llama.rms_norm, ops.rope,
ops.attention.attend — so the stats can never drift from the model.

The embedding and lm_head keep their int8 per-row formats (int4.py
module docstring). Offline entry point: scripts/quantize_checkpoint.py,
which writes the result into the same prepared-weight cache the factory
load path reads.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fasttalk_tpu.quantization.int4 import (pack_int4, quantize_math_group,
                                            unpack_int4)
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("quantization.awq")

# Alpha grid for the norm-fold search; 0.0 is the data-free identity,
# so AWQ can never do worse than the fallback on its own objective.
ALPHA_GRID = tuple(i / 10.0 for i in range(11))
# Clip grid for wo/w_down; 1.0 is the data-free identity.
CLIP_GRID = (1.0, 0.95, 0.9, 0.85, 0.8)
# Calibration rows actually used for the per-candidate output-MSE
# evaluations (the full batch feeds the channel stats, which are cheap).
_EVAL_ROWS = 512


def calibration_tokens(tokenizer: Any, *, n_samples: int = 16,
                       seq_len: int = 256, seed: int = 0,
                       source: str = "corpus") -> jnp.ndarray:
    """[n, seq_len] int32 calibration batch.

    ``source``: "corpus" draws rendered tinychat training conversations
    (training/corpus.py — the distribution the shipped checkpoint was
    trained on); any other value is a path to a UTF-8 text file whose
    non-empty lines are the calibration documents. Token streams are
    PACKED (concatenated, then sliced into rows) rather than padded —
    pad tokens would pollute the channel statistics.
    """
    if source in ("", "corpus"):
        from fasttalk_tpu.training.corpus import corpus_texts

        texts = list(corpus_texts(max(n_samples * 2, 8), seed))
    else:
        with open(source, encoding="utf-8") as f:
            texts = [ln for ln in (l.strip() for l in f) if ln]
        if not texts:
            raise ValueError(
                f"WEIGHT_QUANT_CALIB file {source!r} has no non-empty "
                "lines to calibrate on")
    stream: list[int] = []
    need = n_samples * seq_len
    for text in texts:
        stream.extend(tokenizer.encode(text))
        if len(stream) >= need:
            break
    n = min(n_samples, len(stream) // seq_len)
    if n < 1:
        raise ValueError(
            f"calibration source yielded only {len(stream)} tokens; "
            f"need at least seq_len={seq_len} for one sample")
    arr = jnp.asarray(stream[:n * seq_len], jnp.int32)
    return arr.reshape(n, seq_len)


def _dequant_candidate(w: jnp.ndarray, group: int,
                       clip: float = 1.0) -> jnp.ndarray:
    """Quantize-dequantize ``w`` [K, N] f32 with the group's maxabs
    shrunk by ``clip`` — the reconstruction a served int4 leaf would
    compute, for candidate scoring."""
    k, n = w.shape
    g = w.reshape(k // group, group, n)
    s = jnp.maximum(jnp.max(jnp.abs(g), axis=-2) * clip / 7.0, 1e-8)
    q = jnp.clip(jnp.round(g / s[:, None, :]), -8, 7)
    return (q * s[:, None, :]).reshape(k, n)


def _fold_search(h: jnp.ndarray, weights: dict[str, jnp.ndarray],
                 group: int) -> tuple[float, jnp.ndarray]:
    """Best (alpha, s) for one norm-fed weight group.

    ``h`` [N, K] f32: the calibration activations entering the group's
    matmuls; ``weights``: name -> [K, out] f32. Scores each alpha by
    the summed output MSE of ``(h/s) @ dq(s*W)`` against ``h @ W``.
    """
    m = jnp.maximum(jnp.mean(jnp.abs(h), axis=0), 1e-8)  # [K]
    he = h[:_EVAL_ROWS]
    refs = {name: he @ w for name, w in weights.items()}
    best = (jnp.inf, 0.0, jnp.ones_like(m))
    for alpha in ALPHA_GRID:
        s = m ** alpha
        s = s / jnp.exp(jnp.mean(jnp.log(s)))  # geo-mean 1: pure re-balance
        s = jnp.maximum(s, 1e-4)
        err = 0.0
        hs = he / s[None, :]
        for name, w in weights.items():
            dq = _dequant_candidate(w * s[:, None], group)
            err += float(jnp.mean((hs @ dq - refs[name]) ** 2))
        if err < best[0]:
            best = (err, alpha, s)
    return best[1], best[2]


def _clip_search(h: jnp.ndarray, w: jnp.ndarray, group: int) -> float:
    """Best maxabs-shrink factor for one norm-less weight [K, out]."""
    he = h[:_EVAL_ROWS]
    ref = he @ w
    best = (jnp.inf, 1.0)
    for clip in CLIP_GRID:
        err = float(jnp.mean(
            (he @ _dequant_candidate(w, group, clip) - ref) ** 2))
        if err < best[0]:
            best = (err, clip)
    return best[1]


def _quantize_clipped(w: jnp.ndarray, group: int, clip: float) -> dict:
    """Pack [..., K, N] with the group maxabs shrunk by ``clip``."""
    if clip >= 1.0:
        q, s = quantize_math_group(w, group)
        return {"q4": pack_int4(q), "s": s}
    k, n = w.shape[-2], w.shape[-1]
    g = w.astype(jnp.float32).reshape(w.shape[:-2] + (k // group, group, n))
    s = jnp.maximum(jnp.max(jnp.abs(g), axis=-2) * clip / 7.0, 1e-8)
    q = jnp.clip(jnp.round(g / s[..., None, :]), -8, 7).astype(jnp.int8)
    return {"q4": pack_int4(q.reshape(w.shape[:-2] + (k, n))), "s": s}


def quantize_params_awq(params: dict, cfg: Any, tokens: jnp.ndarray,
                        group: int) -> tuple[dict, dict]:
    """AWQ-calibrated int4 quantization of a FLOAT param pytree.

    ``params``: unquantized pytree (models/loader.py layout, any float
    dtype); ``tokens`` [B, T] from :func:`calibration_tokens`. Returns
    (quantized pytree, manifest dict with the chosen alpha/clip per
    layer and the per-layer output MSEs) — the manifest is what
    scripts/quantize_checkpoint.py writes next to the prepared cache.
    """
    from fasttalk_tpu.models.llama import rms_norm
    from fasttalk_tpu.ops.attention import attend
    from fasttalk_tpu.ops.quant import _quantize_embed, _quantize_head_t
    from fasttalk_tpu.ops.rope import apply_rope, rope_frequencies

    group = int(group)
    layers = params["layers"]
    f32 = lambda a: jnp.asarray(a, jnp.float32)  # noqa: E731
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                            cfg.rope_scaling))
    x = f32(params["embed"])[tokens]
    out_layers: dict[str, list] = {
        name: [] for name in ("attn_norm", "mlp_norm", "wq", "wk", "wv",
                              "wo", "w_gate", "w_up", "w_down")}
    manifest: dict[str, Any] = {"group": group, "layers": []}
    for li in range(cfg.num_layers):
        lp = {name: f32(w[li]) for name, w in layers.items()}
        # --- attention block, float forward with the ORIGINAL weights
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = h @ lp["wq"]
        k = h @ lp["wk"]
        v = h @ lp["wv"]
        if cfg.qkv_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = apply_rope(q.reshape(b, t, cfg.num_heads, cfg.head_dim),
                       positions, inv_freq)
        k = apply_rope(k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim),
                       positions, inv_freq)
        v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        o = attend(q, k, v, positions).reshape(b, t, cfg.q_dim)
        x = x + o @ lp["wo"]
        # --- MLP block
        h2 = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        gate = jax.nn.silu(h2 @ lp["w_gate"])
        up = h2 @ lp["w_up"]
        prod = gate * up
        x = x + prod @ lp["w_down"]

        # --- searches on this layer's captured activations
        h_flat = h.reshape(-1, cfg.hidden_size)
        a_attn, s_attn = _fold_search(
            h_flat, {n: lp[n] for n in ("wq", "wk", "wv")}, group)
        h2_flat = h2.reshape(-1, cfg.hidden_size)
        a_mlp, s_mlp = _fold_search(
            h2_flat, {n: lp[n] for n in ("w_gate", "w_up")}, group)
        c_wo = _clip_search(o.reshape(-1, cfg.q_dim), lp["wo"], group)
        c_down = _clip_search(prod.reshape(-1, cfg.intermediate_size),
                              lp["w_down"], group)
        for name, s in (("wq", s_attn), ("wk", s_attn), ("wv", s_attn),
                        ("w_gate", s_mlp), ("w_up", s_mlp)):
            out_layers[name].append(
                _quantize_clipped(lp[name] * s[:, None], group, 1.0))
        out_layers["attn_norm"].append(lp["attn_norm"] / s_attn)
        out_layers["mlp_norm"].append(lp["mlp_norm"] / s_mlp)
        out_layers["wo"].append(_quantize_clipped(lp["wo"], group, c_wo))
        out_layers["w_down"].append(
            _quantize_clipped(lp["w_down"], group, c_down))
        manifest["layers"].append({
            "layer": li, "alpha_attn": float(a_attn),
            "alpha_mlp": float(a_mlp), "clip_wo": float(c_wo),
            "clip_w_down": float(c_down)})
        log.info(f"AWQ layer {li}: alpha_attn={a_attn:.1f} "
                 f"alpha_mlp={a_mlp:.1f} clip_wo={c_wo:.2f} "
                 f"clip_w_down={c_down:.2f}")

    norm_dtype = params["layers"]["attn_norm"].dtype
    out = dict(params)
    out["layers"] = dict(params["layers"])
    for name, per_layer in out_layers.items():
        if isinstance(per_layer[0], dict):
            out["layers"][name] = {
                "q4": jnp.stack([d["q4"] for d in per_layer]),
                "s": jnp.stack([d["s"] for d in per_layer])}
        else:
            out["layers"][name] = jnp.stack(per_layer).astype(norm_dtype)
    out["embed"] = _quantize_embed(f32(params["embed"]))
    if "lm_head" in out:
        out["lm_head"] = _quantize_head_t(f32(params["lm_head"]))
    return out, manifest


def dequant_error(w4: dict, wf: jnp.ndarray) -> float:
    """Mean-squared weight reconstruction error (tests, manifests)."""
    group = (2 * w4["q4"].shape[-2]) // w4["s"].shape[-2]
    dq = unpack_int4(w4["q4"]).astype(jnp.float32) * jnp.repeat(
        w4["s"].astype(jnp.float32), group, axis=-2)
    return float(jnp.mean((dq - jnp.asarray(wf, jnp.float32)) ** 2))
