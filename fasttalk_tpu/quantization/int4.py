"""Group-wise symmetric int4 weight format: pack/unpack/quantize math.

Storage format (docs/QUANTIZATION.md has the diagram): a matmul leaf
``w: [..., K, N]`` becomes

    {"q4": uint8[..., K/2, N], "s": f32[..., K/G, N]}

* **Symmetric, two's-complement nibbles.** Each weight is rounded to
  [-8, 7] against a per-(group, out-channel) scale ``s = maxabs / 7``.
  -8 is representable but never produced by quantization (maxabs maps
  to ±7), which keeps the codebook symmetric like the int8 tier.
* **Adjacent-pair packing along the contraction axis.** Packed row j
  holds original row 2j in the LOW nibble and row 2j+1 in the HIGH
  nibble. Pairing *adjacent* rows (not split-halves) means a contiguous
  range of packed rows maps to a contiguous range of original rows, so
  the tp partition specs for "q4" are the weight's own specs and a
  shard boundary never splits a nibble pair as long as the shard size
  is even (parallel/sharding.py validates this).
* **Group scales along the same axis.** G contraction rows share one
  f32 scale per out-channel. G must be even (a nibble pair must never
  straddle a group boundary) and divide every contraction dim it is
  applied to — ``validate_group`` checks the model's dims up front.

Only the seven stacked layer matmuls (``INT4_LEAVES``) go to int4. The
embedding table and untied lm_head stay per-row/per-column int8 exactly
as in the int8 tier: the embedding gather wants per-row scales, and the
untied head keeps the transposed int8 layout the streaming Pallas
kernel (``int8_matmul_t``) already serves.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Default contraction-group size; divides every matmul dim of the
# shipped model shapes (tinychat: hidden 256, q_dim 256, intermediate
# 768) and matches the AWQ paper's common setting.
GROUP_DEFAULT = 128

# Stacked per-layer matmul leaves that take the int4 format. Embedding
# and lm_head deliberately excluded (module docstring).
INT4_LEAVES = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"})


def validate_group(model_cfg, group: int) -> None:
    """Raise a named ValueError unless ``group`` fits the model's dims.

    Checked once at engine build / checkpoint quantization so hot-path
    code can assume clean divisibility.
    """
    group = int(group)
    if group < 2 or group % 2:
        raise ValueError(
            f"WEIGHT_QUANT_GROUP must be an even integer >= 2 (int4 packs "
            f"adjacent contraction rows into one byte, so a scale group "
            f"must never split a nibble pair), got {group}")
    dims = {
        "hidden_size (wq/wk/wv/w_gate/w_up contraction)":
            model_cfg.hidden_size,
        "q_dim (wo contraction)": model_cfg.q_dim,
        "intermediate_size (w_down contraction)":
            model_cfg.intermediate_size,
    }
    bad = {name: d for name, d in dims.items() if d % group}
    if bad:
        detail = ", ".join(f"{name}={d}" for name, d in bad.items())
        raise ValueError(
            f"WEIGHT_QUANT_GROUP={group} must divide every matmul "
            f"contraction dim of model '{model_cfg.name}'; it does not "
            f"divide: {detail}. Pick a common divisor (e.g. a power of "
            f"two <= the smallest dim) or use WEIGHT_QUANT=int8.")


def pack_int4(q: jax.Array) -> jax.Array:
    """Pack int8 values in [-8, 7] along axis -2, two per byte.

    ``q: [..., K, N] int8 -> [..., K/2, N] uint8``; packed row j =
    (row 2j+1 << 4) | (row 2j & 0xF).
    """
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    return ((hi.astype(jnp.uint8) & 0xF) << 4) | (lo.astype(jnp.uint8) & 0xF)


def unpack_int4(q4: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: ``[..., K/2, N] uint8 -> [..., K, N]``.

    int8 ``>>`` is arithmetic, so ``(b << 4) >> 4`` sign-extends the low
    nibble and ``b >> 4`` the high one — no lookup table needed.
    """
    b = q4.astype(jnp.int8)
    lo = (b << 4) >> 4
    hi = b >> 4
    kp, n = q4.shape[-2], q4.shape[-1]
    return jnp.stack([lo, hi], axis=-2).reshape(
        q4.shape[:-2] + (2 * kp, n))


def quantize_math_group(wf: jax.Array, group: int):
    """Group-wise symmetric quantize: ``[..., K, N] -> (q int8, s f32)``.

    ``s[..., g, n] = max(|w[..., g*G:(g+1)*G, n]|) / 7`` (clamped away
    from zero so all-zero groups dequantize to exact zeros), ``q`` is
    the rounded ratio clipped to [-8, 7]. Returns q UNPACKED so callers
    can inspect/modify before :func:`pack_int4`.
    """
    k, n = wf.shape[-2], wf.shape[-1]
    g = wf.astype(jnp.float32).reshape(wf.shape[:-2] + (k // group, group, n))
    s = jnp.maximum(jnp.max(jnp.abs(g), axis=-2) / 7.0, 1e-8)
    q = jnp.clip(jnp.round(g / s[..., None, :]), -8, 7).astype(jnp.int8)
    return q.reshape(wf.shape[:-2] + (k, n)), s


def quantize_group(wf: jax.Array, group: int) -> dict:
    """Quantize + pack a float leaf into the ``{"q4", "s"}`` format."""
    q, s = quantize_math_group(wf, group)
    return {"q4": pack_int4(q), "s": s}


def group_size_of(w4: dict) -> int:
    """Recover G from a packed leaf's shapes."""
    return (2 * w4["q4"].shape[-2]) // w4["s"].shape[-2]


def dequantize_int4(w4: dict, dtype=jnp.float32) -> jax.Array:
    """Materialize the float weight (tests/calibration only — the
    serving path dequantizes inside the matmul, ops/quant.py)."""
    group = group_size_of(w4)
    w = unpack_int4(w4["q4"]).astype(dtype)
    return w * jnp.repeat(w4["s"].astype(dtype), group, axis=-2)


@functools.partial(jax.jit, static_argnames=("group",), donate_argnums=(0,))
def _quantize_leaf_int4(w, group):
    return quantize_group(w, group)


def quantize_params_int4(params: dict, group: int) -> dict:
    """Data-free quantization of a full param pytree.

    INT4_LEAVES -> {"q4", "s"}; embedding (and untied lm_head) take the
    int8 tier's per-row / transposed formats so lookups and the
    streaming head kernel keep working. This is the fast fallback for
    random/test weights; calibrated quantization lives in awq.py.
    """
    from fasttalk_tpu.ops.quant import _quantize_embed, _quantize_head_t

    out = dict(params)
    out["layers"] = dict(params["layers"])
    for name, w in out["layers"].items():
        if name in INT4_LEAVES and not isinstance(w, dict):
            out["layers"][name] = _quantize_leaf_int4(w, int(group))
    if not isinstance(out["embed"], dict):
        out["embed"] = _quantize_embed(out["embed"])
    if "lm_head" in out and not isinstance(out["lm_head"], dict):
        out["lm_head"] = _quantize_head_t(out["lm_head"])
    return out


def _np_quantize_group(a: np.ndarray, group: int):
    """Host-side numpy twin of quantize_group (checkpoint load path)."""
    k, n = a.shape[-2], a.shape[-1]
    g = a.astype(np.float32).reshape(a.shape[:-2] + (k // group, group, n))
    s = np.maximum(np.max(np.abs(g), axis=-2) / 7.0, 1e-8).astype(np.float32)
    q = np.clip(np.round(g / s[..., None, :]), -8, 7).astype(np.int8)
    q = q.reshape(a.shape[:-2] + (k, n))
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    q4 = ((hi.astype(np.uint8) & 0xF) << 4) | (lo.astype(np.uint8) & 0xF)
    return q4, s


def quantizing_put_int4(inner_put, raw_put, group: int):
    """Wrap a loader ``put`` hook to quantize leaves host-side.

    Mirrors ops.quant.quantizing_put: matmul leaves go through numpy
    group quantization BEFORE device transfer (only packed bytes +
    scales cross PCIe), embedding/lm_head reuse the int8 putter's
    per-row formats, everything else (norms, biases) flows through
    ``inner_put`` unchanged. ``path`` strings come from
    models/loader.py ("layers/wq", "embed", "lm_head").
    """
    from fasttalk_tpu.ops.quant import quantizing_put

    group = int(group)
    int8_put = quantizing_put(inner_put, raw_put)

    def put(arr, path: str):
        name = path.split("/")[-1]
        if name in INT4_LEAVES:
            a = np.asarray(arr)
            q4, s = _np_quantize_group(a, group)
            return {"q4": raw_put(q4, f"{path}/q4"),
                    "s": raw_put(s, f"{path}/s")}
        # embed / lm_head / norms / biases: int8 tier behaviour.
        return int8_put(arr, path)

    return put


def is_int4(params: dict) -> bool:
    """True when the layer stack carries nibble-packed leaves."""
    layers = params.get("layers", {})
    for name in INT4_LEAVES:
        w = layers.get(name)
        if isinstance(w, dict) and "q4" in w:
            return True
    return False
