"""Run the reference's literal PydanticAI agent against THIS engine.

The reference's production agent is a `pydantic_ai.Agent` over vLLM's
OpenAI endpoint (reference: app/agents/voice_agent.py:85-344, model
wiring :127-139). This framework serves the same OpenAI surface
(`/v1/chat/completions` with `tools`/`tool_choice`, hermes parsing
in-tree), so the identical PydanticAI code runs against the TPU engine —
BASELINE config #4 demonstrated with the real library, not a
shape-compatible imitation.

Usage (needs `pip install fasttalk-tpu[agents]`):

    # terminal 1: the server (any provider; tpu with real weights,
    # or fake for a wiring check)
    LLM_PROVIDER=tpu python main.py websocket

    # terminal 2:
    python examples/pydantic_ai_demo.py [--base-url http://127.0.0.1:8000/v1]

The agent registers a local tool; the model calls it through the served
tools surface and the final streamed answer incorporates the result —
the full client-driven loop: stream → tool_calls → execute client-side →
resume → final text.
"""

from __future__ import annotations

import argparse
import asyncio
import datetime


async def main(base_url: str, model: str) -> None:
    from pydantic_ai import Agent
    from pydantic_ai.models.openai import OpenAIChatModel
    from pydantic_ai.providers.openai import OpenAIProvider

    agent = Agent(
        OpenAIChatModel(
            model,
            provider=OpenAIProvider(base_url=base_url,
                                    api_key="not-needed"),
        ),
        system_prompt=("You are a concise voice assistant. Use tools "
                       "when they help."),
    )

    @agent.tool_plain
    def get_current_time() -> str:
        """Get the current date and time (UTC)."""
        return datetime.datetime.now(datetime.timezone.utc).isoformat()

    async with agent.run_stream("What time is it right now?") as result:
        async for delta in result.stream_text(delta=True):
            print(delta, end="", flush=True)
    print()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-url", default="http://127.0.0.1:8000/v1")
    ap.add_argument("--model", default="llama3.2:1b")
    args = ap.parse_args()
    asyncio.run(main(args.base_url, args.model))
