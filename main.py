#!/usr/bin/env python3
"""FastTalk-TPU service CLI.

Modes (parity with the reference CLI, main.py:29-43):
  websocket  — start the WebSocket streaming service (+ monitoring port)
  config     — show resolved configuration (--show)
  test       — engine smoke test: build, generate a few tokens, exit 0/1

Overrides: --port --host --model --provider --log-level (+ --preset).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="fasttalk-tpu", description=__doc__)
    p.add_argument("mode", choices=["websocket", "config", "test"],
                   nargs="?", default="websocket")
    p.add_argument("--host")
    p.add_argument("--port", type=int)
    p.add_argument("--model")
    p.add_argument("--provider",
                   choices=["tpu", "vllm", "ollama", "fake"])
    p.add_argument("--log-level",
                   choices=["DEBUG", "INFO", "WARNING", "ERROR"])
    p.add_argument("--preset", choices=["fast", "balanced", "quality"])
    p.add_argument("--show", action="store_true",
                   help="config mode: print resolved settings")
    return p.parse_args(argv)


def apply_overrides(args: argparse.Namespace) -> None:
    """CLI flags become env vars before Config resolves (reference:
    main.py:49-61)."""
    if args.host:
        os.environ["LLM_HOST"] = args.host
    if args.port:
        os.environ["LLM_PORT"] = str(args.port)
    if args.model:
        os.environ["LLM_MODEL"] = args.model
    if args.provider:
        os.environ["LLM_PROVIDER"] = args.provider
    if args.log_level:
        os.environ["LOG_LEVEL"] = args.log_level


def run_config(args: argparse.Namespace) -> int:
    import json

    from fasttalk_tpu.utils.config import Config

    cfg = Config()
    if args.preset:
        cfg.apply_preset(args.preset)
    print(json.dumps(cfg.to_dict(), indent=2, default=str))
    return 0


def run_test(args: argparse.Namespace) -> int:
    """Engine connectivity/diagnostic test (reference: main.py:93-197
    probed external backends; here the engine is in-process, so the test
    builds it and generates real tokens)."""
    from fasttalk_tpu.engine.engine import GenerationParams
    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.utils.config import Config
    from fasttalk_tpu.utils.logger import configure_logging, get_logger

    cfg = Config()
    configure_logging(cfg.log_level)
    log = get_logger("main.test")
    log.info(f"Building engine: provider={cfg.llm_provider} "
             f"model={cfg.model_name} device={cfg.compute_device}")
    try:
        engine = build_engine(cfg)
        engine.start()
        if not engine.check_connection():
            log.error("Engine failed connectivity check")
            return 1
        info = engine.get_model_info()
        log.info(f"Engine ready: {info}")

        async def probe() -> int:
            n = 0
            async for ev in engine.generate(
                    "selftest", "selftest",
                    [{"role": "user", "content": "Hello!"}],
                    GenerationParams(max_tokens=8, temperature=0.0,
                                     top_k=0, top_p=1.0)):
                if ev["type"] == "token":
                    n += 1
                if ev["type"] == "error":
                    raise RuntimeError(ev.get("error"))
            return n

        chunks = asyncio.run(probe())
        log.info(f"Generated {chunks} stream chunks — engine OK")
        engine.shutdown()
        print("OK")
        return 0
    except Exception as e:
        log.error(f"Engine test failed: {e}", exc_info=True)
        print("FAILED")
        return 1


def run_websocket(args: argparse.Namespace) -> int:
    # Multi-host first: jax.distributed must initialise before ANY jax
    # call (Config's device detection touches the backend). No-op
    # without cluster env.
    from fasttalk_tpu.parallel.distributed import maybe_initialize

    maybe_initialize()

    from fasttalk_tpu.serving.launcher import ServerLauncher
    from fasttalk_tpu.utils.config import Config
    from fasttalk_tpu.utils.logger import configure_logging, get_logger

    cfg = Config()
    if args.preset:
        cfg.apply_preset(args.preset)
    configure_logging(cfg.log_level, log_path=cfg.log_path or None)
    log = get_logger("main")
    log.info(f"Starting FastTalk-TPU: provider={cfg.llm_provider} "
             f"model={cfg.model_name} device={cfg.compute_device} "
             f"port={cfg.port} monitoring={cfg.monitoring_port}")
    if cfg.spmd_role == "follower":
        from fasttalk_tpu.serving.launcher import run_spmd_follower

        return run_spmd_follower(cfg)
    ServerLauncher(cfg).start()
    return 0


def main(argv: list[str] | None = None) -> int:
    args = parse_args(argv)
    apply_overrides(args)
    if args.mode == "config":
        return run_config(args)
    if args.mode == "test":
        return run_test(args)
    return run_websocket(args)


if __name__ == "__main__":
    sys.exit(main())
