@echo off
REM Run FastTalk-TPU on a CPU-only Windows host (development).
REM Mirror of run-cpu.sh (reference shipped run-cpu.bat the same way).
cd /d "%~dp0"

if not exist ".venv" (
    python -m venv .venv
)
call .venv\Scripts\activate.bat

python -c "import jax" 2>NUL
if errorlevel 1 (
    pip install --quiet --upgrade pip
    pip install --quiet -e .
)

if "%OMP_NUM_THREADS%"=="" set OMP_NUM_THREADS=%NUMBER_OF_PROCESSORS%
set JAX_PLATFORMS=cpu
set COMPUTE_DEVICE=cpu
if "%LLM_PROVIDER%"=="" set LLM_PROVIDER=tpu
if "%LLM_MODEL%"=="" set LLM_MODEL=test-tiny
if "%TPU_DTYPE%"=="" set TPU_DTYPE=float32
if "%TPU_DECODE_SLOTS%"=="" set TPU_DECODE_SLOTS=4
if "%TPU_MAX_MODEL_LEN%"=="" set TPU_MAX_MODEL_LEN=2048

python main.py websocket %*
