#!/usr/bin/env python3
"""Scripted E2E client for the FastTalk-TPU WebSocket service.

Plays the role of the reference's manual test client
(test_llm_client.py — which needed interactive input) as a
non-interactive script usable in CI: health check, full protocol
exercise (session_started → start_session → session_configured →
user_message → token stream → response_complete → end_session), exit
code 0/1.

Usage: python client.py [--url ws://localhost:8000/ws/llm]
                        [--prompt "..."] [--max-tokens N] [--quiet]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import aiohttp


async def check_health(base_url: str, quiet: bool) -> bool:
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base_url}/health",
                             timeout=aiohttp.ClientTimeout(total=10)) as r:
                body = await r.json()
                if not quiet:
                    print(f"health: {body.get('status')} "
                          f"(model={body.get('model')})")
                return r.status == 200
    except Exception as e:
        print(f"health check failed: {e}", file=sys.stderr)
        return False


async def run_session(ws_url: str, prompt: str, max_tokens: int,
                      quiet: bool) -> bool:
    async with aiohttp.ClientSession() as session:
        async with session.ws_connect(ws_url) as ws:
            msg = json.loads((await ws.receive()).data)
            assert msg["type"] == "session_started", msg
            if not quiet:
                print(f"session: {msg['session_id']} "
                      f"(provider={msg.get('provider')})")

            await ws.send_json({
                "type": "start_session",
                "config": {
                    "system_prompt": "You are a concise assistant.",
                    "max_tokens": max_tokens,
                },
            })
            msg = json.loads((await ws.receive()).data)
            assert msg["type"] == "session_configured", msg

            await ws.send_json({"type": "user_message", "text": prompt})
            tokens = 0
            stats = {}
            while True:
                raw = await ws.receive()
                if raw.type != aiohttp.WSMsgType.TEXT:
                    print(f"unexpected frame: {raw.type}", file=sys.stderr)
                    return False
                msg = json.loads(raw.data)
                if msg["type"] == "token":
                    tokens += 1
                    if not quiet:
                        print(msg.get("data", ""), end="", flush=True)
                elif msg["type"] == "response_complete":
                    stats = msg.get("stats", {})
                    break
                elif msg["type"] == "error":
                    print(f"\nerror: {msg.get('error')}", file=sys.stderr)
                    return False
            if not quiet:
                print(f"\nstats: {stats.get('tokens_generated')} tok, "
                      f"{stats.get('tokens_per_second', 0):.1f} tok/s, "
                      f"ttft {stats.get('ttft_ms', 0):.0f} ms")

            await ws.send_json({"type": "end_session"})
            msg = json.loads((await ws.receive()).data)
            assert msg["type"] == "session_ended", msg
            return True


async def amain(args: argparse.Namespace) -> int:
    base = args.url.replace("ws://", "http://").replace(
        "wss://", "https://").rsplit("/ws/", 1)[0]
    if not await check_health(base, args.quiet):
        return 1
    ok = await run_session(args.url, args.prompt, args.max_tokens,
                           args.quiet)
    if ok and not args.quiet:
        print("E2E OK")
    return 0 if ok else 1


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--url", default="ws://localhost:8000/ws/llm")
    p.add_argument("--prompt", default="Write a haiku about oceans.")
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--quiet", action="store_true")
    return asyncio.run(amain(p.parse_args()))


if __name__ == "__main__":
    sys.exit(main())
