#!/usr/bin/env python3
"""Scripted E2E client for the FastTalk-TPU WebSocket service.

Plays the role of the reference's manual test client
(test_llm_client.py — which needed interactive input) as a
non-interactive script usable in CI: health check, full protocol
exercise (session_started → start_session → session_configured →
user_message → token stream → response_complete → end_session), exit
code 0/1.

Back-off discipline (docs/SCHEDULING.md, docs/ROUTER.md): capacity
rejections — an error frame carrying ``retry_after`` (429-class
shedding, connection limit) or a WebSocket close with code 1013 — are
honoured with reconnect-and-backoff instead of exiting, so the client
survives a routed failover or an overload burst the way a production
caller should. Mid-stream ``resumed`` frames (fleet failover moved the
stream to a surviving replica) are informational: the stream continues.

Usage: python client.py [--url ws://localhost:8000/ws/llm]
                        [--prompt "..."] [--max-tokens N] [--quiet]
                        [--retries N]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys

import aiohttp

# WS close code 1013 "try again later" — the server's connection-limit
# rejection (serving/server.py) closes with this after the error frame.
TRY_AGAIN_LATER = 1013


class Backoff(Exception):
    """A capacity rejection carrying the server's retry_after hint."""

    def __init__(self, retry_after: float, why: str):
        super().__init__(why)
        self.retry_after = retry_after
        self.why = why


async def check_health(base_url: str, quiet: bool) -> bool:
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base_url}/health",
                             timeout=aiohttp.ClientTimeout(total=10)) as r:
                body = await r.json()
                if not quiet:
                    print(f"health: {body.get('status')} "
                          f"(model={body.get('model')})")
                return r.status == 200
    except Exception as e:
        print(f"health check failed: {e}", file=sys.stderr)
        return False


def _maybe_backoff(msg: dict) -> None:
    """Raise Backoff when an error frame is a capacity rejection (it
    carries retry_after: shed, connection limit, breaker open)."""
    err = msg.get("error") or {}
    retry_after = err.get("retry_after")
    if retry_after is not None:
        raise Backoff(float(retry_after),
                      f"{err.get('code', 'rejected')}: "
                      f"{err.get('message', '')}")


async def run_session(ws_url: str, prompt: str, max_tokens: int,
                      quiet: bool) -> bool:
    async with aiohttp.ClientSession() as session:
        async with session.ws_connect(ws_url) as ws:
            first = await ws.receive()
            if first.type != aiohttp.WSMsgType.TEXT:
                # Closed before the greeting: treat 1013 as backoff.
                if ws.close_code == TRY_AGAIN_LATER:
                    raise Backoff(5.0, "server closed 1013 (try later)")
                print(f"unexpected frame: {first.type}", file=sys.stderr)
                return False
            msg = json.loads(first.data)
            if msg["type"] == "error":
                _maybe_backoff(msg)  # connection-limit rejection
                print(f"error: {msg.get('error')}", file=sys.stderr)
                return False
            assert msg["type"] == "session_started", msg
            if not quiet:
                print(f"session: {msg['session_id']} "
                      f"(provider={msg.get('provider')})")

            await ws.send_json({
                "type": "start_session",
                "config": {
                    "system_prompt": "You are a concise assistant.",
                    "max_tokens": max_tokens,
                },
            })
            msg = json.loads((await ws.receive()).data)
            assert msg["type"] == "session_configured", msg

            await ws.send_json({"type": "user_message", "text": prompt})
            tokens = 0
            stats = {}
            while True:
                raw = await ws.receive()
                if raw.type != aiohttp.WSMsgType.TEXT:
                    if ws.close_code == TRY_AGAIN_LATER:
                        raise Backoff(5.0,
                                      "server closed 1013 (try later)")
                    print(f"unexpected frame: {raw.type}", file=sys.stderr)
                    return False
                msg = json.loads(raw.data)
                if msg["type"] == "token":
                    tokens += 1
                    if not quiet:
                        print(msg.get("data", ""), end="", flush=True)
                elif msg["type"] == "resumed":
                    # Fleet failover: the stream moved to a surviving
                    # replica; keep reading — this is not an error.
                    if not quiet:
                        print(f"\n[resumed on {msg.get('replica')}] ",
                              end="", flush=True)
                elif msg["type"] == "response_complete":
                    stats = msg.get("stats", {})
                    break
                elif msg["type"] == "error":
                    _maybe_backoff(msg)
                    print(f"\nerror: {msg.get('error')}", file=sys.stderr)
                    return False
            if not quiet:
                print(f"\nstats: {stats.get('tokens_generated')} tok, "
                      f"{stats.get('tokens_per_second', 0):.1f} tok/s, "
                      f"ttft {stats.get('ttft_ms', 0):.0f} ms")

            await ws.send_json({"type": "end_session"})
            msg = json.loads((await ws.receive()).data)
            assert msg["type"] == "session_ended", msg
            return True


async def run_with_backoff(ws_url: str, prompt: str, max_tokens: int,
                           quiet: bool, retries: int) -> bool:
    """run_session, honouring server retry_after hints: sleep and
    reconnect up to ``retries`` times before giving up."""
    for attempt in range(retries + 1):
        try:
            return await run_session(ws_url, prompt, max_tokens, quiet)
        except Backoff as b:
            if attempt >= retries:
                print(f"giving up after {retries} retries: {b.why}",
                      file=sys.stderr)
                return False
            # Honour the hint, bounded, with jitter so a shed burst of
            # clients doesn't reconnect in lockstep.
            delay = min(30.0, max(0.1, b.retry_after))
            delay *= 1.0 + random.uniform(0.0, 0.25)
            print(f"backing off {delay:.1f}s ({b.why})", file=sys.stderr)
            await asyncio.sleep(delay)
        except aiohttp.ClientError as e:
            if attempt >= retries:
                print(f"connection failed: {e}", file=sys.stderr)
                return False
            delay = min(5.0, 0.5 * (2 ** attempt))
            print(f"reconnecting in {delay:.1f}s ({e})", file=sys.stderr)
            await asyncio.sleep(delay)
    return False


async def amain(args: argparse.Namespace) -> int:
    base = args.url.replace("ws://", "http://").replace(
        "wss://", "https://").rsplit("/ws/", 1)[0]
    if not await check_health(base, args.quiet):
        return 1
    ok = await run_with_backoff(args.url, args.prompt, args.max_tokens,
                                args.quiet, args.retries)
    if ok and not args.quiet:
        print("E2E OK")
    return 0 if ok else 1


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--url", default="ws://localhost:8000/ws/llm")
    p.add_argument("--prompt", default="Write a haiku about oceans.")
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--retries", type=int, default=3,
                   help="reconnect-and-backoff attempts on capacity "
                        "rejections (retry_after / close 1013)")
    return asyncio.run(amain(p.parse_args()))


if __name__ == "__main__":
    sys.exit(main())
