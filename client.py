#!/usr/bin/env python3
"""Scripted E2E client for the FastTalk-TPU WebSocket service.

Plays the role of the reference's manual test client
(test_llm_client.py — which needed interactive input) as a
non-interactive script usable in CI: health check, full protocol
exercise (session_started → start_session → session_configured →
user_message → token stream → response_complete → end_session), exit
code 0/1.

Back-off discipline (docs/SCHEDULING.md, docs/ROUTER.md): capacity
rejections — an error frame carrying ``retry_after`` (429-class
shedding, connection limit) or a WebSocket close with code 1013 — are
honoured with reconnect-and-backoff instead of exiting, so the client
survives a routed failover or an overload burst the way a production
caller should. Mid-stream ``resumed`` frames (fleet failover moved the
stream to a surviving replica) are informational: the stream continues.

``--journey`` (docs/OBSERVABILITY.md "the token journey") opts the
session into per-token attribution and prints BOTH waterfalls side by
side: the server's hop decomposition (device retire → fetch →
detokenize → loop dequeue → WS write, from response_complete stats)
and the client's own receive timeline. Each token frame then carries
a server wall-clock stamp ("st"); min(client_recv_wall - st) over the
stream estimates the one-way network delay + clock offset, splitting
measured server time from network RTT.

Usage: python client.py [--url ws://localhost:8000/ws/llm]
                        [--prompt "..."] [--max-tokens N] [--quiet]
                        [--retries N] [--journey]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import time

import aiohttp

# WS close code 1013 "try again later" — the server's connection-limit
# rejection (serving/server.py) closes with this after the error frame.
TRY_AGAIN_LATER = 1013


class Backoff(Exception):
    """A capacity rejection carrying the server's retry_after hint."""

    def __init__(self, retry_after: float, why: str):
        super().__init__(why)
        self.retry_after = retry_after
        self.why = why


async def check_health(base_url: str, quiet: bool) -> bool:
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base_url}/health",
                             timeout=aiohttp.ClientTimeout(total=10)) as r:
                body = await r.json()
                if not quiet:
                    print(f"health: {body.get('status')} "
                          f"(model={body.get('model')})")
                return r.status == 200
    except Exception as e:
        print(f"health check failed: {e}", file=sys.stderr)
        return False


def _maybe_backoff(msg: dict) -> None:
    """Raise Backoff when an error frame is a capacity rejection (it
    carries retry_after: shed, connection limit, breaker open)."""
    err = msg.get("error") or {}
    retry_after = err.get("retry_after")
    if retry_after is not None:
        raise Backoff(float(retry_after),
                      f"{err.get('code', 'rejected')}: "
                      f"{err.get('message', '')}")


class ClientJourney:
    """Client-side half of the token journey: per-token receive
    timestamps (monotonic for inter-token gaps, wall for the network
    split against the server's "st" stamps)."""

    def __init__(self) -> None:
        self.t0_mono = time.monotonic()
        self.recv_mono: list[float] = []
        # (client wall at receive) - (server wall at send), per frame.
        # Network one-way delay + clock offset; min() over the stream
        # is the tightest estimate of the constant part, so
        # (delta - min_delta) is per-token network jitter.
        self.deltas: list[float] = []

    def on_token(self, msg: dict) -> None:
        now_mono = time.monotonic()
        self.recv_mono.append(now_mono)
        st = msg.get("st")
        if isinstance(st, (int, float)):
            self.deltas.append(time.time() - float(st))

    @staticmethod
    def _pctl(vals: list[float], q: float) -> float:
        if not vals:
            return 0.0
        s = sorted(vals)
        return s[min(len(s) - 1, max(0, int(round(q * len(s) + 0.5)) - 1))]

    def report(self, server_journey: dict | None) -> str:
        lines = ["", "--- token journey (client view) ---"]
        n = len(self.recv_mono)
        if not n:
            return "\n".join(lines + ["no tokens received"])
        ttft_ms = (self.recv_mono[0] - self.t0_mono) * 1000
        gaps = [(b - a) * 1000 for a, b in
                zip(self.recv_mono, self.recv_mono[1:])]
        lines.append(f"client TTFT            {ttft_ms:9.1f} ms "
                     f"({n} tokens)")
        if gaps:
            lines.append(f"inter-token p50/p99    "
                         f"{self._pctl(gaps, 0.50):9.1f} / "
                         f"{self._pctl(gaps, 0.99):.1f} ms")
        if self.deltas:
            base = min(self.deltas)
            jitter = [(d - base) * 1000 for d in self.deltas]
            lines.append(f"network+offset (min)   {base * 1000:9.1f} ms "
                         "(one-way delay + clock offset)")
            lines.append(f"network jitter p50/p99 "
                         f"{self._pctl(jitter, 0.50):9.1f} / "
                         f"{self._pctl(jitter, 0.99):.1f} ms")
        if server_journey:
            lines.append("--- token journey (server hops) ---")
            hops = server_journey.get("hops_ms", {})
            for hop, ms in hops.items():
                lines.append(f"{hop:<22} {float(ms):9.1f} ms total")
            lines.append(
                f"server wall {float(server_journey.get('wall_ms', 0)):.1f} "
                f"ms, hop sum {float(server_journey.get('hops_sum_ms', 0)):.1f}"
                f" ms, reconciliation "
                f"{float(server_journey.get('reconciliation', 0)):.3f}")
            sttft = server_journey.get("ttft_ms")
            if sttft is not None:
                lines.append(
                    f"server TTFT {float(sttft):.1f} ms vs client "
                    f"{ttft_ms:.1f} ms → network+client share "
                    f"{ttft_ms - float(sttft):.1f} ms")
        return "\n".join(lines)


async def run_session(ws_url: str, prompt: str, max_tokens: int,
                      quiet: bool, journey: bool = False) -> bool:
    async with aiohttp.ClientSession() as session:
        async with session.ws_connect(ws_url) as ws:
            first = await ws.receive()
            if first.type != aiohttp.WSMsgType.TEXT:
                # Closed before the greeting: treat 1013 as backoff.
                if ws.close_code == TRY_AGAIN_LATER:
                    raise Backoff(5.0, "server closed 1013 (try later)")
                print(f"unexpected frame: {first.type}", file=sys.stderr)
                return False
            msg = json.loads(first.data)
            if msg["type"] == "error":
                _maybe_backoff(msg)  # connection-limit rejection
                print(f"error: {msg.get('error')}", file=sys.stderr)
                return False
            assert msg["type"] == "session_started", msg
            if not quiet:
                print(f"session: {msg['session_id']} "
                      f"(provider={msg.get('provider')})")

            config = {
                "system_prompt": "You are a concise assistant.",
                "max_tokens": max_tokens,
            }
            if journey:
                config["journey"] = True
            await ws.send_json({"type": "start_session",
                                "config": config})
            msg = json.loads((await ws.receive()).data)
            assert msg["type"] == "session_configured", msg

            jc = ClientJourney() if journey else None
            await ws.send_json({"type": "user_message", "text": prompt})
            tokens = 0
            stats = {}
            while True:
                raw = await ws.receive()
                if raw.type != aiohttp.WSMsgType.TEXT:
                    if ws.close_code == TRY_AGAIN_LATER:
                        raise Backoff(5.0,
                                      "server closed 1013 (try later)")
                    print(f"unexpected frame: {raw.type}", file=sys.stderr)
                    return False
                msg = json.loads(raw.data)
                if msg["type"] == "token":
                    tokens += 1
                    if jc is not None:
                        jc.on_token(msg)
                    if not quiet:
                        print(msg.get("data", ""), end="", flush=True)
                elif msg["type"] == "resumed":
                    # Fleet failover: the stream moved to a surviving
                    # replica; keep reading — this is not an error.
                    if not quiet:
                        print(f"\n[resumed on {msg.get('replica')}] ",
                              end="", flush=True)
                elif msg["type"] == "response_complete":
                    stats = msg.get("stats", {})
                    break
                elif msg["type"] == "error":
                    _maybe_backoff(msg)
                    print(f"\nerror: {msg.get('error')}", file=sys.stderr)
                    return False
            if not quiet:
                print(f"\nstats: {stats.get('tokens_generated')} tok, "
                      f"{stats.get('tokens_per_second', 0):.1f} tok/s, "
                      f"ttft {stats.get('ttft_ms', 0):.0f} ms")
            if jc is not None:
                print(jc.report(stats.get("journey")))

            await ws.send_json({"type": "end_session"})
            msg = json.loads((await ws.receive()).data)
            assert msg["type"] == "session_ended", msg
            return True


async def run_with_backoff(ws_url: str, prompt: str, max_tokens: int,
                           quiet: bool, retries: int,
                           journey: bool = False) -> bool:
    """run_session, honouring server retry_after hints: sleep and
    reconnect up to ``retries`` times before giving up."""
    for attempt in range(retries + 1):
        try:
            return await run_session(ws_url, prompt, max_tokens, quiet,
                                     journey=journey)
        except Backoff as b:
            if attempt >= retries:
                print(f"giving up after {retries} retries: {b.why}",
                      file=sys.stderr)
                return False
            # Honour the hint, bounded, with jitter so a shed burst of
            # clients doesn't reconnect in lockstep.
            delay = min(30.0, max(0.1, b.retry_after))
            delay *= 1.0 + random.uniform(0.0, 0.25)
            print(f"backing off {delay:.1f}s ({b.why})", file=sys.stderr)
            await asyncio.sleep(delay)
        except aiohttp.ClientError as e:
            if attempt >= retries:
                print(f"connection failed: {e}", file=sys.stderr)
                return False
            delay = min(5.0, 0.5 * (2 ** attempt))
            print(f"reconnecting in {delay:.1f}s ({e})", file=sys.stderr)
            await asyncio.sleep(delay)
    return False


async def amain(args: argparse.Namespace) -> int:
    base = args.url.replace("ws://", "http://").replace(
        "wss://", "https://").rsplit("/ws/", 1)[0]
    if not await check_health(base, args.quiet):
        return 1
    ok = await run_with_backoff(args.url, args.prompt, args.max_tokens,
                                args.quiet, args.retries,
                                journey=args.journey)
    if ok and not args.quiet:
        print("E2E OK")
    return 0 if ok else 1


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--url", default="ws://localhost:8000/ws/llm")
    p.add_argument("--prompt", default="Write a haiku about oceans.")
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--retries", type=int, default=3,
                   help="reconnect-and-backoff attempts on capacity "
                        "rejections (retry_after / close 1013)")
    p.add_argument("--journey", action="store_true",
                   help="opt into per-token journey attribution and "
                        "print client vs server waterfalls (network "
                        "RTT split)")
    return asyncio.run(amain(p.parse_args()))


if __name__ == "__main__":
    sys.exit(main())
